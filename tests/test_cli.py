"""CLI + FsShell + examples driver ≈ bin/hadoop dispatch, FsShell.java,
ExampleDriver.java (SURVEY.md §1 L8)."""

import io
import json
import os

import numpy as np
import pytest

from tpumr.cli import main as cli_main
from tpumr.fs import get_filesystem
from tpumr.fs.shell import FsShell


def run_shell(*argv, default_fs=None):
    out, err = io.StringIO(), io.StringIO()
    sh = FsShell(default_fs=default_fs, out=out, err=err)
    rc = sh.run(list(argv))
    return rc, out.getvalue(), err.getvalue()


class TestFsShell:
    def test_mkdir_ls_put_cat(self, tmp_path):
        local = tmp_path / "src.txt"
        local.write_text("hello shell\n")
        rc, _, _ = run_shell("-mkdir", "mem:///sh/dir")
        assert rc == 0
        rc, _, _ = run_shell("-put", str(local), "mem:///sh/dir/a.txt")
        assert rc == 0
        rc, out, _ = run_shell("-cat", "mem:///sh/dir/a.txt")
        assert rc == 0 and out == "hello shell\n"
        rc, out, _ = run_shell("-ls", "mem:///sh/dir")
        assert rc == 0 and "a.txt" in out

    def test_get_cp_mv_rm(self, tmp_path):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/t/x.txt", b"data")
        dst = tmp_path / "out.txt"
        rc, _, _ = run_shell("-get", "mem:///t/x.txt", str(dst))
        assert rc == 0 and dst.read_bytes() == b"data"
        rc, _, _ = run_shell("-cp", "mem:///t/x.txt", "mem:///t/y.txt")
        assert rc == 0 and fs.read_bytes("/t/y.txt") == b"data"
        rc, _, _ = run_shell("-mv", "mem:///t/y.txt", "mem:///t/z.txt")
        assert rc == 0 and not fs.exists("/t/y.txt")
        rc, _, _ = run_shell("-rm", "mem:///t/z.txt")
        assert rc == 0 and not fs.exists("/t/z.txt")

    def test_du_count_test(self):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/d/a", b"xx")
        fs.write_bytes("/d/b", b"yyy")
        rc, out, _ = run_shell("-du", "mem:///d")
        assert rc == 0 and "total 5" in out
        rc, out, _ = run_shell("-count", "mem:///d")
        assert rc == 0
        assert run_shell("-test", "-e", "mem:///d/a")[0] == 0
        assert run_shell("-test", "-e", "mem:///d/nope")[0] == 1
        assert run_shell("-test", "-d", "mem:///d")[0] == 0

    def test_default_fs_resolution(self):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/base/f.txt", b"resolved")
        rc, out, _ = run_shell("-cat", "/base/f.txt", default_fs="mem://")
        assert rc == 0 and out == "resolved"

    def test_glob(self):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/g/part-00000", b"a\n")
        fs.write_bytes("/g/part-00001", b"b\n")
        rc, out, _ = run_shell("-cat", "mem:///g/part-*")
        assert rc == 0 and out == "a\nb\n"

    def test_unknown_and_missing(self):
        rc, _, err = run_shell("-bogus")
        assert rc == 255 and "unknown command" in err
        rc, _, err = run_shell("-cat", "mem:///nope")
        assert rc == 1


class TestCliDispatch:
    def test_version(self, capsys):
        assert cli_main(["version"]) == 0
        assert "tpumr" in capsys.readouterr().out

    def test_unknown(self, capsys):
        assert cli_main(["frobnicate"]) == 255

    def test_generic_options_fs(self, capsys):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/cli/hello.txt", b"via cli")
        rc = cli_main(["-fs", "mem://", "fs", "-cat", "/cli/hello.txt"])
        assert rc == 0
        assert capsys.readouterr().out == "via cli"


class TestJobControl:
    def test_job_list_and_status(self, capsys):
        from tpumr.mapred.job_client import JobClient
        from tpumr.mapred.mini_cluster import MiniMRCluster
        with MiniMRCluster(num_trackers=1, cpu_slots=2, tpu_slots=0) as c:
            fs = get_filesystem("mem:///")
            fs.write_bytes("/jc/in.txt", b"a b c\n" * 50)
            conf = c.create_job_conf()
            conf.set_input_paths("mem:///jc/in.txt")
            conf.set_output_path("mem:///jc/out")
            from tpumr.ops.wordcount import WordCountCpuMapper
            from tpumr.examples.basic import LongSumReducer
            conf.set_mapper_class(WordCountCpuMapper)
            conf.set_reducer_class(LongSumReducer)
            result = JobClient(conf).run_job(conf)
            assert result.successful
            jt = c.master_address
            assert cli_main(["-jt", jt, "job", "-list"]) == 0
            out = capsys.readouterr().out
            assert "job_" in out and "SUCCEEDED" in out
            jid = out.split()[0]
            assert cli_main(["-jt", jt, "job", "-status", jid]) == 0
            status = json.loads(capsys.readouterr().out)
            assert status["state"] == "SUCCEEDED"
            assert cli_main(["-jt", jt, "job", "-counters", jid]) == 0


class TestExamples:
    def test_driver_lists(self, capsys):
        assert cli_main(["examples", "-h"]) == 0
        err = capsys.readouterr().err
        assert "wordcount" in err and "kmeans" in err

    def test_wordcount(self, capsys):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/ex/in.txt", b"apple pear apple\npear apple\n")
        rc = cli_main(["examples", "wordcount",
                       "mem:///ex/in.txt", "mem:///ex/out"])
        assert rc == 0
        text = fs.read_bytes("/ex/out/part-00000").decode()
        counts = dict(line.split("\t") for line in text.splitlines())
        assert counts == {"apple": "3", "pear": "2"}

    def test_pi(self, capsys):
        rc = cli_main(["examples", "pi", "4", "500",
                       "--work", "mem:///ex/pi"])
        assert rc == 0
        out = capsys.readouterr().out
        est = float(out.strip().rsplit(" ", 1)[1])
        assert 2.5 < est < 3.8

    def test_kmeans_converges(self, capsys):
        from tpumr.examples.basic import save_npy
        fs = get_filesystem("mem:///")
        rng = np.random.default_rng(7)
        pts = np.concatenate([
            rng.normal(loc=(0, 0), scale=0.05, size=(60, 2)),
            rng.normal(loc=(9, 9), scale=0.05, size=(60, 2)),
        ]).astype(np.float32)
        rng.shuffle(pts)
        save_npy(fs, "/ex/km/points.npy", pts)
        rc = cli_main(["examples", "kmeans", "mem:///ex/km/points.npy",
                       "mem:///ex/km/out", "-k", "2", "-i", "3",
                       "--split-rows", "50"])
        assert rc == 0
        from tpumr.examples.basic import load_npy
        cents = load_npy(fs, "mem:///ex/km/out/centroids.npy")
        cents = cents[np.argsort(cents[:, 0])]
        np.testing.assert_allclose(cents[0], (0, 0), atol=0.2)
        np.testing.assert_allclose(cents[1], (9, 9), atol=0.2)

    def test_grep(self, capsys):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/ex/g/in.txt", b"foo123 bar foo456\nbaz foo789\n")
        rc = cli_main(["examples", "grep", "mem:///ex/g/in.txt",
                       "mem:///ex/g/out", r"foo\d+"])
        assert rc == 0
        text = fs.read_bytes("/ex/g/out/part-00000").decode()
        assert len(text.splitlines()) == 3

    def test_matmul(self):
        from tpumr.examples.basic import load_npy, save_npy
        fs = get_filesystem("mem:///")
        rng = np.random.default_rng(3)
        a = rng.normal(size=(32, 16)).astype(np.float32)
        b = rng.normal(size=(16, 8)).astype(np.float32)
        save_npy(fs, "/ex/mm/a.npy", a)
        save_npy(fs, "/ex/mm/b.npy", b)
        rc = cli_main(["examples", "matmul", "mem:///ex/mm/a.npy",
                       "mem:///ex/mm/b.npy", "mem:///ex/mm/out",
                       "--split-rows", "16", "--cpu-only"])
        assert rc == 0
        outs = [st for st in fs.list_files("/ex/mm/out")
                if st.path.name.startswith("part")]
        assert outs


def test_job_history_viewer(tmp_path, capsys):
    """≈ hadoop job -history / HistoryViewer: offline summary of one
    job's history file, including per-attempt failure rows."""
    from tpumr.cli import main as cli
    from tpumr.fs import get_filesystem
    from tpumr.mapred.job_client import JobClient
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.mini_cluster import MiniMRCluster

    hist = tmp_path / "hist"
    conf0 = JobConf()
    conf0.set("tpumr.history.dir", str(hist))
    fs = get_filesystem("mem:///")
    fs.write_bytes("/jh/in.txt", b"x y\n" * 20)
    with MiniMRCluster(num_trackers=1, conf=conf0, cpu_slots=2,
                       tpu_slots=0) as c:
        conf = c.create_job_conf()
        conf.set_job_name("history-viewer-job")
        conf.set_input_paths("mem:///jh/in.txt")
        conf.set_output_path("mem:///jh/out")
        conf.set("mapred.mapper.class",
                 "tpumr.ops.wordcount.WordCountCpuMapper")
        conf.set("mapred.reducer.class",
                 "tpumr.examples.basic.LongSumReducer")
        result = JobClient(conf).run_job(conf)
        assert result.successful
        job_id = str(result.job_id)

    rc = cli(["job", "-history", job_id, str(hist)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "history-viewer-job" in out
    assert "SUCCEEDED" in out
    assert "JOB_FINISHED=1" in out

    rc = cli(["job", "-history", "job_nope_0001", str(hist)])
    assert rc == 1
    assert "known:" in capsys.readouterr().err


class TestSiteConfigLoading:
    """≈ HADOOP_CONF_DIR *-site.xml auto-loading + GenericOptionsParser
    -conf: site files layer below -conf files below -D overrides."""

    def test_conf_dir_and_dash_conf_precedence(self, tmp_path,
                                               monkeypatch, capsys):
        import json as _json

        from tpumr.cli import main as cli_main
        site = tmp_path / "tpumr-site.json"
        site.write_text(_json.dumps({"k.site": "from-site",
                                     "k.both": "site"}))
        extra = tmp_path / "extra.json"
        extra.write_text(_json.dumps({"k.both": "conf-file",
                                      "k.d": "conf-file"}))
        monkeypatch.setenv("TPUMR_CONF_DIR", str(tmp_path))
        # inject a probe command that records what conf it was handed
        from tpumr.core.configuration import Configuration
        seen = {}

        def probe_cmd(conf, argv):
            seen["site"] = conf.get("k.site")
            seen["both"] = conf.get("k.both")
            seen["d"] = conf.get("k.d")
            return 0

        import tpumr.cli as cli_mod
        monkeypatch.setitem(cli_mod.COMMANDS, "probeconf", probe_cmd)
        depth = len(Configuration._default_resources)
        rc = cli_main(["-conf", str(extra), "-D", "k.d=dash-d",
                       "probeconf"])
        assert rc == 0
        assert seen == {"site": "from-site", "both": "conf-file",
                        "d": "dash-d"}
        # layers removed after the invocation (no accumulation)
        assert len(Configuration._default_resources) == depth

    def test_missing_dash_conf_fails_loudly(self, tmp_path, capsys):
        from tpumr.cli import main as cli_main
        with pytest.raises(OSError):
            cli_main(["-conf", str(tmp_path / "nope.json"), "version"])

    def test_partial_conf_failure_leaks_no_layers(self, tmp_path):
        import json as _json

        from tpumr.cli import main as cli_main
        from tpumr.core.configuration import Configuration
        ok = tmp_path / "a.json"
        ok.write_text(_json.dumps({"x": 1}))
        depth = len(Configuration._default_resources)
        with pytest.raises(OSError):
            cli_main(["-conf", str(ok),
                      "-conf", str(tmp_path / "missing.json"), "version"])
        assert len(Configuration._default_resources) == depth
