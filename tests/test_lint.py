"""tpulint — the analyzer must catch each seeded violation class and
stay quiet on known-good (and pragma'd) code, and the repo itself must
lint clean (the CI gate's contract)."""

import json
import os
import textwrap
from pathlib import Path

import pytest

from tpumr.core import confkeys
from tpumr.tools.tpulint.clockcheck import check_clock
from tpumr.tools.tpulint.confcheck import check_conf
from tpumr.tools.tpulint.core import apply_pragmas, load_corpus
from tpumr.tools.tpulint.driftcheck import (check_fi_drift,
                                            check_metric_drift)
from tpumr.tools.tpulint.lockcheck import check_locks

REPO_ROOT = str(Path(__file__).resolve().parent.parent)


def write_tree(root: Path, files: "dict[str, str]") -> None:
    for rel, body in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))


def lint_files(tmp_path: Path, files: "dict[str, str]", checker,
               **kw):
    write_tree(tmp_path, files)
    mods = load_corpus(str(tmp_path), ("tpumr",))
    if checker in (check_conf, check_metric_drift, check_fi_drift):
        findings = checker(mods, str(tmp_path), **kw)
    else:
        findings = checker(mods, **kw)
    return apply_pragmas(mods, findings)


# --------------------------------------------------------------- lock rank

LOCK_PRELUDE = """\
    from tpumr.metrics.locks import (RANK_GLOBAL, RANK_SCHEDULER,
                                     RANK_JOB, InstrumentedRLock)

    class Master:
        def __init__(self):
            self.lock = InstrumentedRLock(name="global",
                                          rank=RANK_GLOBAL)
            self.sched_lock = InstrumentedRLock(name="scheduler",
                                                rank=RANK_SCHEDULER)
            self.job_lock = InstrumentedRLock(name="job", rank=RANK_JOB)
"""


def test_lock_order_direct_inversion(tmp_path):
    found = lint_files(tmp_path, {"tpumr/mapred/bad.py": LOCK_PRELUDE + """\

        def bad(self):
            with self.lock:
                with self.sched_lock:
                    pass
    """}, check_locks)
    assert [f.rule for f in found] == ["lock-order"]
    assert "'scheduler' (rank 10)" in found[0].message
    assert "'global' (rank 20)" in found[0].message


def test_lock_order_ascending_is_legal(tmp_path):
    found = lint_files(tmp_path, {"tpumr/mapred/good.py": LOCK_PRELUDE + """\

        def good(self):
            with self.sched_lock:
                with self.lock:
                    with self.job_lock:
                        pass
    """}, check_locks)
    assert found == []


def test_lock_order_two_hop_call_chain(tmp_path):
    """The case the runtime assertion misses on unexercised paths: the
    inversion is only reachable through a TWO-hop call chain."""
    found = lint_files(tmp_path, {"tpumr/mapred/chain.py": LOCK_PRELUDE + """\

        def holder(self):
            with self.job_lock:
                self.hop1()

        def hop1(self):
            self.hop2()

        def hop2(self):
            with self.sched_lock:
                pass
    """}, check_locks)
    rules = [f.rule for f in found]
    assert "lock-order" in rules
    order = next(f for f in found if f.rule == "lock-order")
    assert "'job' (rank 40)" in order.message
    assert "'scheduler' (rank 10)" in order.message
    # the chain names both hops so the path is actionable
    assert any("hop1" in hop for hop in order.chain)
    assert any("hop2" in hop for hop in order.chain)


def test_lock_blocking_direct_and_chained(tmp_path):
    found = lint_files(tmp_path, {"tpumr/mapred/blk.py": LOCK_PRELUDE + """\

        def direct(self):
            import time
            with self.sched_lock:
                time.sleep(0.5)

        def chained(self):
            with self.lock:
                self.notify()

        def notify(self):
            import time
            time.sleep(0.1)
    """}, check_locks)
    blocking = [f for f in found if f.rule == "lock-blocking"]
    assert len(blocking) == 2
    assert all("time.sleep" in f.message for f in blocking)


def test_lock_blocking_rpc_under_lock(tmp_path):
    found = lint_files(tmp_path, {"tpumr/mapred/rpc_hold.py":
                                  LOCK_PRELUDE + """\

        def bad(self, client):
            with self.lock:
                client.call("get_task")
    """}, check_locks)
    assert [f.rule for f in found] == ["lock-blocking"]
    assert "RPC" in found[0].message


def test_lock_nested_def_is_deferred_work(tmp_path):
    """Code inside a nested def under a with-block runs LATER (thread
    target, callback) — it must not be charged to the lock region."""
    found = lint_files(tmp_path, {"tpumr/mapred/defer.py":
                                  LOCK_PRELUDE + """\

        def ok(self):
            import time
            with self.lock:
                def later():
                    time.sleep(5)
                self.pending = later
    """}, check_locks)
    assert found == []


def test_lock_pragma_suppresses(tmp_path):
    found = lint_files(tmp_path, {"tpumr/mapred/prag.py": LOCK_PRELUDE + """\

        def excused(self):
            with self.lock:
                with self.sched_lock:  # tpulint: disable=lock-order
                    pass
    """}, check_locks)
    assert found == []


def test_lock_ranks_parsed_from_locks_py(tmp_path):
    """The rank table comes from tpumr/metrics/locks.py — a fixture
    declaring an INVERTED numbering must flip the verdict."""
    files = {
        "tpumr/metrics/locks.py": """\
            RANK_GLOBAL = 10
            RANK_SCHEDULER = 20
        """,
        "tpumr/mapred/m.py": """\
            from tpumr.metrics.locks import (RANK_GLOBAL, RANK_SCHEDULER,
                                             InstrumentedRLock)

            class M:
                def __init__(self):
                    self.lock = InstrumentedRLock(name="global",
                                                  rank=RANK_GLOBAL)
                    self.sched_lock = InstrumentedRLock(
                        name="scheduler", rank=RANK_SCHEDULER)

                def f(self):
                    with self.lock:
                        with self.sched_lock:
                            pass
        """,
    }
    assert lint_files(tmp_path, files, check_locks) == []


# ------------------------------------------------------------------- conf

def test_conf_unregistered_key_with_suggestion(tmp_path):
    found = lint_files(tmp_path, {"tpumr/mapred/c.py": """\
        def f(conf):
            return conf.get_int("tpumr.hartbeat.interval.ms", 1000)
    """}, check_conf)
    keyed = [f for f in found if f.rule == "conf-key"]
    assert len(keyed) == 1
    assert "tpumr.heartbeat.interval.ms" in keyed[0].message


def test_conf_registered_key_passes(tmp_path):
    found = lint_files(tmp_path, {"tpumr/mapred/c.py": """\
        def f(conf):
            return conf.get_int("tpumr.heartbeat.interval.ms", 1000)
    """}, check_conf)
    assert [f for f in found if f.rule == "conf-key"] == []


def test_conf_conflicting_defaults_across_files(tmp_path):
    files = {
        "tpumr/mapred/a.py": """\
            def f(conf):
                return conf.get_int("tpumr.zz.unregistered.knob", 5)
        """,
        "tpumr/mapred/b.py": """\
            def g(conf):
                return conf.get_int("tpumr.zz.unregistered.knob", 9)
        """,
    }
    found = lint_files(tmp_path, files, check_conf)
    conflicts = [f for f in found if f.rule == "conf-default"]
    assert len(conflicts) == 1
    assert "conflicting defaults" in conflicts[0].message


def test_conf_default_contradicting_registry(tmp_path):
    found = lint_files(tmp_path, {"tpumr/mapred/c.py": """\
        def f(conf):
            return conf.get_int("tpumr.heartbeat.interval.ms", 9999)
    """}, check_conf)
    bad = [f for f in found if f.rule == "conf-default"]
    assert len(bad) == 1
    assert "registry says 1000" in bad[0].message


def test_conf_pragma_suppresses(tmp_path):
    found = lint_files(tmp_path, {"tpumr/mapred/c.py": """\
        def f(conf):
            return conf.get("tpumr.zz.bogus")  # tpulint: disable=conf-key
    """}, check_conf)
    assert [f for f in found if f.rule == "conf-key"] == []


def test_conf_unread_registered_key(tmp_path, monkeypatch):
    ghost = confkeys.ConfKey("tpumr.zz.ghost.knob", "int", 1, "unused")
    monkeypatch.setitem(confkeys.REGISTRY, ghost.key, ghost)
    found = lint_files(tmp_path, {"tpumr/mapred/c.py": """\
        def f(conf):
            return conf.get_int("tpumr.heartbeat.interval.ms", 1000)
    """}, check_conf)
    unread = [f for f in found if f.rule == "conf-unread"]
    assert any("tpumr.zz.ghost.knob" in f.message for f in unread)


def test_conf_dynamic_fi_key_matches_pattern(tmp_path):
    found = lint_files(tmp_path, {"tpumr/mapred/c.py": """\
        def f(conf, point):
            return conf.get(f"tpumr.fi.{point}.probability")
    """}, check_conf)
    assert [f for f in found if f.rule == "conf-key"] == []


def test_conf_example_phantom_key(tmp_path):
    write_tree(tmp_path, {"conf/tpumr-site.example.toml": """\
        [tpumr.zz]
        "phantom.knob" = 1
    """})
    found = lint_files(tmp_path, {"tpumr/mapred/c.py": "X = 1\n"},
                       check_conf)
    phantom = [f for f in found if f.rule == "conf-example"]
    assert len(phantom) == 1
    assert "tpumr.zz.phantom.knob" in phantom[0].message


# ------------------------------------------------------------------ clock

def test_clock_deadline_arith_flagged(tmp_path):
    found = lint_files(tmp_path, {"tpumr/w.py": """\
        import time

        def bad_deadline():
            return time.time() + 30

        def bad_compare(deadline):
            return time.time() > deadline

        def bad_tainted_var(start):
            t0 = time.time()
            return t0 - start
    """}, check_clock)
    assert [f.rule for f in found] == ["clock-arith"] * 3


def test_clock_good_samples_pass(tmp_path):
    found = lint_files(tmp_path, {"tpumr/w.py": """\
        import time

        def stamp_only():
            return {"ts": time.time()}

        def monotonic_deadline():
            return time.monotonic() + 30

        def scaled_stamp():
            return int(time.time() * 1000)
    """}, check_clock)
    assert found == []


def test_clock_pragma_suppresses(tmp_path):
    found = lint_files(tmp_path, {"tpumr/w.py": """\
        import time

        def display_age(last_seen):
            # human-facing status age off a persisted wall stamp
            return time.time() - last_seen  # tpulint: disable=clock-arith
    """}, check_clock)
    assert found == []


def test_clock_file_level_pragma(tmp_path):
    found = lint_files(tmp_path, {"tpumr/w.py": """\
        # tpulint: disable=clock-arith — absolute wall lifetimes module
        import time

        def a():
            return time.time() + 1

        def b():
            return time.time() - 1
    """}, check_clock)
    assert found == []


# ------------------------------------------------------------------ drift

def test_metric_drift_flags_unknown_only(tmp_path):
    files = {
        "tpumr/m.py": """\
            def setup(reg):
                reg.incr("frobnication_total")
                reg.histogram("frob_seconds")
        """,
        "docs/OPERATIONS.md": """\
            Watch `tpumr_frob_seconds` and `frobnication_total`; the
            `ghost_metric_total` series was renamed away.
        """,
    }
    found = lint_files(tmp_path, files, check_metric_drift)
    assert [f.rule for f in found] == ["drift-metric"]
    assert "ghost_metric_total" in found[0].message


def test_fi_drift_flags_unfired_seam(tmp_path):
    files = {
        "tpumr/utils/fi.py": '''\
            """Fault seams:
              good.seam / good.seam.m<idx>
              ghost.seam
            """

            def maybe_fail(point, conf=None):
                pass
        ''',
        "tpumr/mapred/m.py": """\
            from tpumr.utils.fi import maybe_fail

            def f(conf, idx):
                maybe_fail("good.seam", conf)
                maybe_fail(f"good.seam.m{idx}", conf)
        """,
    }
    found = lint_files(tmp_path, files, check_fi_drift)
    assert [f.rule for f in found] == ["drift-fi"]
    assert "ghost.seam" in found[0].message


# --------------------------------------------------------------- registry

def test_confkeys_lookup_and_patterns():
    assert confkeys.lookup("tpumr.heartbeat.interval.ms").default == 1000
    assert confkeys.lookup("tpumr.fi.tpu.execute.probability").pattern
    assert confkeys.lookup("tpumr.totally.unknown") is None


def test_confkeys_suggest_typo():
    assert "tpumr.heartbeat.interval.ms" in \
        confkeys.suggest("tpumr.hartbeat.interval.ms")


def test_confkeys_typed_readers_on_dict_and_conf():
    from tpumr.core.configuration import Configuration
    assert confkeys.get_int({}, "tpumr.heartbeat.interval.ms") == 1000
    assert confkeys.get_int({"tpumr.heartbeat.interval.ms": "250"},
                            "tpumr.heartbeat.interval.ms") == 250
    assert confkeys.get_boolean({"mapred.speculative.execution": "false"},
                                "mapred.speculative.execution") is False
    conf = Configuration(load_defaults=False)
    conf.set("tpumr.shuffle.copy.retries", 7)
    assert confkeys.get_int(conf, "tpumr.shuffle.copy.retries") == 7
    assert confkeys.get_float(conf, "tpumr.shuffle.copy.backoff.ms") \
        == 200.0


def test_lock_cycle_does_not_poison_memo(tmp_path):
    """A mutually-recursive pair must not get a truncated summary
    memoized by an early query — the inversion through the cycle has
    to surface for later callers (the false-negative class: CI green
    on a real deadlock path)."""
    found = lint_files(tmp_path, {"tpumr/mapred/cyc.py": LOCK_PRELUDE + """\

        def early(self):
            # forces a query of ping/pong while pong is mid-recursion;
            # scheduler(10) held, cycle acquires scheduler -> no report
            with self.sched_lock:
                self.ping()

        def ping(self, n=0):
            if n:
                self.pong(n)
            with self.sched_lock:
                pass

        def pong(self, n):
            self.ping(n - 1)

        def late(self):
            # job(40) held; the cycle's scheduler(10) acquisition MUST
            # still be visible here
            with self.job_lock:
                self.pong(3)
    """}, check_locks)
    assert any(f.rule == "lock-order" and "'job' (rank 40)" in f.message
               for f in found), found


def test_foreign_root_uses_its_own_registry(tmp_path):
    """Linting another checkout judges its code against ITS
    tpumr/core/confkeys.py, not this process's imported registry."""
    files = {
        "tpumr/core/confkeys.py": """\
            from dataclasses import dataclass


            @dataclass(frozen=True)
            class ConfKey:
                key: str
                type: str
                default: object
                doc: str
                pattern: bool = False


            REGISTRY = {e.key: e for e in [
                ConfKey("tpumr.branch.only.knob", "int", 5, "new key"),
            ]}


            def lookup(key):
                return REGISTRY.get(key)


            def pattern_matches(p, k):
                return False


            def pattern_covers(p, k):
                return False


            def suggest(key, n=3, cutoff=4):
                return []
        """,
        "tpumr/mapred/c.py": """\
            def f(conf):
                return conf.get_int("tpumr.branch.only.knob", 5)
        """,
    }
    found = lint_files(tmp_path, files, check_conf)
    assert [f for f in found if f.rule == "conf-key"] == []
    # and the repo's registry keys are NOT demanded of the foreign tree
    assert all("tpumr.heartbeat" not in f.message for f in found)


def test_parse_error_is_a_finding(tmp_path):
    """A broken file must FAIL lint — an empty tree would silently
    disable every other rule for that file."""
    from tpumr.tools.tpulint.core import parse_error_findings
    write_tree(tmp_path, {"tpumr/broken.py": """\
        def broken(:
            return time.time() + 30
    """})
    mods = load_corpus(str(tmp_path), ("tpumr",))
    found = parse_error_findings(mods)
    assert [f.rule for f in found] == ["parse-error"]
    assert found[0].path == "tpumr/broken.py"


def test_conf_unread_anchors_at_registry_line(tmp_path, monkeypatch):
    """The finding must point at the _K(...) entry to delete, not at
    line 1 of the registry."""
    from tpumr.tools.tpulint.confcheck import _registry_source
    mods = load_corpus(REPO_ROOT, ("tpumr",))
    rel, lines = _registry_source(mods)
    assert rel.endswith("core/confkeys.py")
    assert len(lines) > 200   # every shipped entry is mapped
    assert lines["tpumr.heartbeat.interval.ms"] > 1


def test_speculative_reduces_parses_string_false():
    """'-D mapred.reduce.speculative.execution=false' arrives as the
    STRING 'false' in the job's dict conf — it must disable reduce
    speculation (bool('false') truthiness was the old bug)."""
    from tpumr.mapred.ids import JobID
    from tpumr.mapred.job_in_progress import JobInProgress
    jip = JobInProgress(
        JobID("t", 1),
        {"mapred.reduce.speculative.execution": "false"}, splits=[])
    assert jip.speculative is True
    assert jip.speculative_reduces is False
    jip2 = JobInProgress(JobID("t", 2), {}, splits=[])
    assert jip2.speculative_reduces is True   # follows the master switch


# ------------------------------------------------------------- repo gate

def test_repo_lints_clean():
    """The CI contract: `tpumr lint` exits 0 on the repo itself."""
    from tpumr.tools.tpulint.cli import main
    assert main(["--root", REPO_ROOT]) == 0


def test_cli_json_report(tmp_path):
    from tpumr.tools.tpulint.cli import main
    out = tmp_path / "findings.json"
    rc = main(["--root", REPO_ROOT, "--rules", "conf-key",
               "--json", str(out)])
    assert rc == 0
    report = json.loads(out.read_text())
    assert report["rules"] == ["conf-key"]
    assert report["findings"] == []


def test_cli_unknown_rule_is_usage_error():
    from tpumr.tools.tpulint.cli import main
    assert main(["--rules", "no-such-rule"]) == 2


def test_conf_doc_generation(tmp_path):
    from tpumr.tools.tpulint.cli import write_conf_doc
    out = tmp_path / "CONFIG.md"
    assert write_conf_doc(REPO_ROOT, str(out)) == 0
    text = out.read_text()
    assert "`tpumr.heartbeat.interval.ms`" in text
    assert "GENERATED" in text
    # committed copy must be regenerated (the CI diff gate)
    committed = Path(REPO_ROOT) / "docs" / "CONFIG.md"
    assert committed.read_text() == text
