"""Queue administration surface: ``tpumr queue`` / ``mradmin
-refreshQueues`` / ``daemonlog`` (≈ bin/hadoop queue — JobQueueClient
over JobClient.getQueues/getJobsFromQueue/getQueueAclsForCurrentUser;
AdminOperationsProtocol.refreshQueues; the LogLevel servlet)."""

import json
import urllib.request

import pytest

from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.jobtracker import JobMaster
from tpumr.mapred.queue_manager import QueueManager
from tpumr.security import UserGroupInformation


def ugi(user, groups=()):
    return UserGroupInformation(user, list(groups))


@pytest.fixture()
def master():
    conf = JobConf()
    conf.set("mapred.acls.enabled", True)
    conf.set("mapred.queue.names", "default,prod")
    conf.set("mapred.queue.prod.acl-submit-job", "alice")
    conf.set("mapred.queue.prod.acl-administer-jobs", "opsuser")
    conf.set("mapred.cluster.administrators", "root0")
    m = JobMaster(conf).start()
    yield m
    m.stop()


def submit(master, user, queue="prod"):
    return master.submit_job(
        {"mapred.job.queue.name": queue, "user.name": user,
         "mapred.reduce.tasks": 0}, [{"locations": []}])


class TestQueueInfo:
    def test_list_reports_acls_and_counts(self, master):
        jid = submit(master, "alice")
        info = {q["queue"]: q for q in master.get_queue_info()}
        assert set(info) == {"default", "prod"}
        assert info["prod"]["acl_submit_job"] == "alice"
        assert info["prod"]["acl_administer_jobs"] == "opsuser"
        assert info["prod"]["total_jobs"] == 1
        assert info["default"]["total_jobs"] == 0
        assert info["default"]["acl_submit_job"] == "*"  # unset = open
        assert jid in master.get_queue_jobs("prod")
        assert master.get_queue_jobs("default") == []

    def test_showacls_per_user(self, master):
        rows = {r["queue"]: r["operations"]
                for r in master.get_queue_acls("alice")}
        assert rows["prod"] == ["submit-job"]
        assert set(rows["default"]) == {"submit-job", "administer-jobs"}
        rows = {r["queue"]: r["operations"]
                for r in master.get_queue_acls("opsuser")}
        assert rows["prod"] == ["administer-jobs"]
        # cluster administrators hold every operation everywhere
        rows = {r["queue"]: r["operations"]
                for r in master.get_queue_acls("root0")}
        assert set(rows["prod"]) == {"submit-job", "administer-jobs"}


class TestRefreshQueues:
    def test_refresh_requires_admin_when_acls_on(self, master):
        with pytest.raises(PermissionError, match="administrator"):
            master.refresh_queues("alice")
        assert master.refresh_queues("root0") == ["default", "prod"]

    def test_refresh_rereads_acls_file(self, tmp_path):
        """The hot-reload path ≈ mapred-queue-acls.xml: ACL changes in
        mapred.queue.acls.file take effect on refresh, no restart."""
        acls = tmp_path / "queue-acls.json"
        acls.write_text(json.dumps(
            {"mapred.queue.prod.acl-submit-job": "alice"}))
        conf = JobConf()
        conf.set("mapred.acls.enabled", True)
        conf.set("mapred.queue.names", "prod")
        conf.set("mapred.cluster.administrators", "admin0")
        conf.set("mapred.queue.acls.file", str(acls))
        m = JobMaster(conf).start()
        try:
            submit(m, "alice")
            with pytest.raises(PermissionError, match="cannot submit"):
                submit(m, "bob")
            # operator edits the file, then mradmin -refreshQueues
            acls.write_text(json.dumps(
                {"mapred.queue.prod.acl-submit-job": "alice,bob"}))
            with pytest.raises(PermissionError, match="cannot submit"):
                submit(m, "bob")        # not yet refreshed
            m.refresh_queues("admin0")
            submit(m, "bob")
        finally:
            m.stop()

    def test_refresh_admin_gate_uses_acl_file_admins(self, tmp_path):
        """With ACLs on and no cluster administrators configured,
        refresh is denied (blank admin ACL allows no one) — the closed
        default, matching every other admin-gated operation."""
        conf = JobConf()
        conf.set("mapred.acls.enabled", True)
        conf.set("mapred.queue.names", "prod")
        m = JobMaster(conf).start()
        try:
            with pytest.raises(PermissionError, match="administrator"):
                m.refresh_queues("anyone")
        finally:
            m.stop()


class TestQueueManagerAclsFile:
    def test_file_layer_beats_startup_conf(self, tmp_path):
        acls = tmp_path / "acls.json"
        acls.write_text(json.dumps(
            {"mapred.queue.q.acl-submit-job": "fileuser"}))
        conf = JobConf()
        conf.set("mapred.queue.names", "q")
        conf.set("mapred.acls.enabled", True)
        conf.set("mapred.queue.acls.file", str(acls))
        qm = QueueManager(conf)
        assert qm.acl_spec("q", "submit-job") == "fileuser"
        assert qm.has_access("q", "submit-job", ugi("fileuser"))
        assert not qm.has_access("q", "submit-job", ugi("other"))

    def test_missing_file_fails_loudly(self):
        conf = JobConf()
        conf.set("mapred.queue.acls.file", "/nonexistent/acls.json")
        with pytest.raises(OSError):
            QueueManager(conf)


class TestDaemonLogEndpoint:
    def test_get_and_set_level_over_http(self):
        import logging

        from tpumr.http import StatusHttpServer
        srv = StatusHttpServer("test").start()
        try:
            host, port = srv.address
            base = f"http://{host}:{port}/json/logLevel"
            name = "tpumr.test.daemonlog"
            with urllib.request.urlopen(f"{base}?log={name}") as r:
                body = json.loads(r.read())
            assert body["log"] == name and body["level"] == "UNSET"
            # a GET can never mutate (drive-by <img> protection): 405
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{base}?log={name}&level=DEBUG")
            assert ei.value.code == 405
            assert logging.getLogger(name).level == logging.NOTSET
            req = urllib.request.Request(
                f"{base}?log={name}&level=DEBUG", method="POST")
            with urllib.request.urlopen(req) as r:
                body = json.loads(r.read())
            assert body["level"] == "DEBUG"
            assert logging.getLogger(name).level == logging.DEBUG
        finally:
            srv.stop()
            logging.getLogger("tpumr.test.daemonlog").setLevel(
                logging.NOTSET)

    def test_daemonlog_cli(self, capsys):
        from tpumr.cli import main as cli_main
        from tpumr.http import StatusHttpServer
        srv = StatusHttpServer("test").start()
        try:
            host, port = srv.address
            rc = cli_main(["daemonlog", "-setlevel", f"{host}:{port}",
                           "tpumr.test.dlcli", "WARNING"])
            assert rc == 0
            assert "level=WARNING" in capsys.readouterr().out
            rc = cli_main(["daemonlog", "-getlevel", f"{host}:{port}",
                           "tpumr.test.dlcli"])
            assert rc == 0
            assert "effective=WARNING" in capsys.readouterr().out
        finally:
            srv.stop()


class TestQueueCli:
    def test_queue_list_and_showacls_over_rpc(self, master, capsys):
        from tpumr.cli import main as cli_main
        submit(master, "alice")
        host, port = master.address
        rc = cli_main(["-jt", f"{host}:{port}", "queue", "-list"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "Queue: prod" in out and "acl-submit-job: alice" in out
        assert "1 running / 1 total" in out or "0 running / 1 total" in out
        rc = cli_main(["-jt", f"{host}:{port}", "queue", "-info", "prod",
                       "-showJobs"])
        assert rc == 0
        out = capsys.readouterr().out
        assert '"queue": "prod"' in out and "job_" in out
        rc = cli_main(["-jt", f"{host}:{port}", "queue", "-showacls"])
        assert rc == 0
        assert "Queue acls for user" in capsys.readouterr().out

    def test_mradmin_refresh_over_rpc(self, master, capsys, monkeypatch):
        from tpumr.cli import main as cli_main
        host, port = master.address
        # the CLI asserts the process user; make it the configured admin
        monkeypatch.setattr(
            "tpumr.security.UserGroupInformation.get_current_user",
            staticmethod(lambda: ugi("root0")))
        rc = cli_main(["-jt", f"{host}:{port}", "mradmin",
                       "-refreshQueues"])
        assert rc == 0
        assert "Queues refreshed: default, prod" in capsys.readouterr().out

class TestSetJobPriority:
    def test_owner_sets_priority_others_denied(self, master):
        jid = submit(master, "alice")
        assert master.set_job_priority(jid, "high", "alice") == "HIGH"
        st = master.get_job_status(jid)
        assert st["priority"] == "HIGH"
        # opsuser holds prod's administer ACL -> allowed
        assert master.set_job_priority(jid, "LOW", "opsuser") == "LOW"
        with pytest.raises(PermissionError, match="cannot administer"):
            master.set_job_priority(jid, "NORMAL", "mallory")
        with pytest.raises(ValueError, match="unknown job priority"):
            master.set_job_priority(jid, "URGENT", "alice")

    def test_cli_set_priority(self, master, capsys, monkeypatch):
        from tpumr.cli import main as cli_main
        jid = submit(master, "alice")
        host, port = master.address
        monkeypatch.setattr(
            "tpumr.security.UserGroupInformation.get_current_user",
            staticmethod(lambda: ugi("alice")))
        rc = cli_main(["-jt", f"{host}:{port}", "job", "-set-priority",
                       jid, "VERY_HIGH"])
        assert rc == 0
        assert "to VERY_HIGH" in capsys.readouterr().out
        rc = cli_main(["-jt", f"{host}:{port}", "job", "-list"])
        assert rc == 0
        assert "VERY_HIGH" in capsys.readouterr().out


class TestRefreshNodes:
    def test_excluded_host_refused_at_contact(self, tmp_path):
        """≈ DisallowedTaskTrackerException at initial contact."""
        excl = tmp_path / "exclude.txt"
        excl.write_text("badhost\n")
        conf = JobConf()
        conf.set("mapred.hosts.exclude", str(excl))
        m = JobMaster(conf).start()
        try:
            resp = m.heartbeat({"tracker_name": "t1", "host": "badhost",
                                "task_statuses": []}, True, True, 0)
            assert resp["actions"] == [{"type": "disallowed"}]
            assert "t1" not in m.trackers
            resp = m.heartbeat({"tracker_name": "t2", "host": "goodhost",
                                "task_statuses": []}, True, True, 0)
            assert {"type": "disallowed"} not in resp["actions"]
            assert "t2" in m.trackers
        finally:
            m.stop()

    def test_include_list_admits_only_listed(self, tmp_path):
        inc = tmp_path / "include.txt"
        inc.write_text("# comment\nnodeA\n")
        conf = JobConf()
        conf.set("mapred.hosts", str(inc))
        m = JobMaster(conf).start()
        try:
            ok = m.heartbeat({"tracker_name": "a", "host": "nodeA",
                              "task_statuses": []}, True, True, 0)
            assert {"type": "disallowed"} not in ok["actions"]
            no = m.heartbeat({"tracker_name": "b", "host": "nodeB",
                              "task_statuses": []}, True, True, 0)
            assert no["actions"] == [{"type": "disallowed"}]
        finally:
            m.stop()

    def test_refresh_nodes_evicts_live_tracker(self, tmp_path):
        """Operator adds a host to the exclude file, runs
        -refreshNodes: the registered tracker is evicted and later
        heartbeats are refused."""
        excl = tmp_path / "exclude.txt"
        excl.write_text("")
        conf = JobConf()
        conf.set("mapred.hosts.exclude", str(excl))
        m = JobMaster(conf).start()
        try:
            m.heartbeat({"tracker_name": "t1", "host": "node1",
                         "shuffle_port": 1, "task_statuses": []},
                        True, True, 0)
            assert "t1" in m.trackers
            excl.write_text("node1\n")
            r = m.refresh_nodes()
            assert r["evicted_trackers"] == ["t1"]
            assert "t1" not in m.trackers
            resp = m.heartbeat({"tracker_name": "t1", "host": "node1",
                                "task_statuses": []}, False, True, 1)
            assert resp["actions"] == [{"type": "disallowed"}]
        finally:
            m.stop()

    def test_refresh_nodes_admin_gated(self, master):
        with pytest.raises(PermissionError, match="administrator"):
            master.refresh_nodes("alice")
        r = master.refresh_nodes("root0")
        assert r["included"] == "*" and r["excluded"] == []

    def test_disallowed_noderunner_shuts_down(self, tmp_path):
        """End-to-end through a real NodeRunner: after -refreshNodes
        excludes its host, the next heartbeat returns 'disallowed' and
        the tracker stops heartbeating (the reference TaskTracker's
        shutdown on DisallowedTaskTrackerException)."""
        import time

        from tpumr.mapred.mini_cluster import MiniMRCluster
        excl = tmp_path / "exclude.txt"
        excl.write_text("")
        conf = JobConf()
        conf.set("mapred.hosts.exclude", str(excl))
        cluster = MiniMRCluster(num_trackers=1, conf=conf,
                                cpu_slots=1, tpu_slots=0,
                                hosts=["nodeX"])
        try:
            deadline = time.time() + 5
            while time.time() < deadline and not cluster.master.trackers:
                time.sleep(0.05)
            assert cluster.master.trackers
            excl.write_text("nodeX\n")
            cluster.master.refresh_nodes()
            tracker = cluster.trackers[0]
            deadline = time.time() + 5
            while time.time() < deadline and not tracker._stop.is_set():
                time.sleep(0.05)
            assert tracker._stop.is_set(), \
                "NodeRunner should stop after being disallowed"
            assert not cluster.master.trackers
        finally:
            cluster.shutdown()

    def test_hosts_file_indented_comment_ignored(self, tmp_path):
        inc = tmp_path / "include.txt"
        inc.write_text("   # managed by config mgmt\n")
        conf = JobConf()
        conf.set("mapred.hosts", str(inc))
        m = JobMaster(conf).start()
        try:
            # comment-only include file = empty = admit all
            r = m.heartbeat({"tracker_name": "t", "host": "any",
                             "task_statuses": []}, True, True, 0)
            assert {"type": "disallowed"} not in r["actions"]
        finally:
            m.stop()


class TestKillFailTask:
    def _cluster_job(self, tmp_path, max_attempts=4):
        """A real mini cluster running one long sleep map."""
        import os
        import threading
        import time as _t

        from tpumr.mapred.job_client import JobClient
        from tpumr.mapred.mini_cluster import MiniMRCluster
        os.makedirs(f"{tmp_path}/in", exist_ok=True)
        with open(f"{tmp_path}/in/f.txt", "w") as f:
            f.write("x\n")
        cluster = MiniMRCluster(num_trackers=1, cpu_slots=1, tpu_slots=0)
        conf = JobConf()
        conf.set_job_name("victim")
        conf.set_input_paths(f"file://{tmp_path}/in")
        conf.set_output_path(f"file://{tmp_path}/out")
        conf.set("mapred.mapper.class",
                 "tpumr.examples.sleep.SleepMapper")
        conf.set("tpumr.sleep.map.ms", 8000)
        conf.set("mapred.reduce.tasks", 0)
        conf.set("mapred.map.max.attempts", max_attempts)
        conf.set("mapred.job.tracker", "%s:%d" % cluster.master.address)
        result = {}

        def _run():
            try:
                result["r"] = JobClient(conf).run_job(conf)
            except RuntimeError as e:   # run_job raises on job failure
                result["error"] = str(e)

        t = threading.Thread(target=_run)
        t.start()
        deadline = _t.time() + 10
        aid = None
        while _t.time() < deadline and aid is None:
            for jip in cluster.master.jobs.values():
                ids = [a for tip in jip.maps
                       for a, s in tip.attempts.items()
                       if s.state == "RUNNING"]
                if ids:
                    aid = ids[0]
            _t.sleep(0.05)
        assert aid is not None, "no running attempt appeared"
        return cluster, t, result, aid

    def test_kill_task_requeues_without_burning_attempt(self, tmp_path):
        import time as _t
        cluster, t, result, aid = self._cluster_job(tmp_path)
        try:
            jip = next(iter(cluster.master.jobs.values()))
            assert cluster.master.kill_task(aid) is True
            # unknown attempt (same job) -> False, not an exception
            assert cluster.master.kill_task(aid[:-1] + "9") is False
            deadline = _t.time() + 15
            while _t.time() < deadline and aid not in {
                    a for tip in jip.maps for a, s in tip.attempts.items()
                    if s.state in ("KILLED", "FAILED")}:
                _t.sleep(0.1)
            states = {a: s.state for tip in jip.maps
                      for a, s in tip.attempts.items()}
            assert states[aid] == "KILLED"
            assert jip.maps[0].failures == 0    # no attempt burned
            t.join(30)
            assert result.get("r") is not None and result["r"].successful
        finally:
            cluster.shutdown()

    def test_fail_task_counts_and_fails_job_at_limit(self, tmp_path):
        import time as _t
        cluster, t, result, aid = self._cluster_job(tmp_path,
                                                    max_attempts=1)
        try:
            assert cluster.master.kill_task(aid, should_fail=True)
            t.join(30)
            jip = next(iter(cluster.master.jobs.values()))
            assert jip.maps[0].failures == 1    # the -fail-task burn
            assert "error" in result            # limit 1 -> job FAILED
            assert "failed 1 times" in result["error"]
        finally:
            cluster.shutdown()


class TestTrackerListings:
    def test_active_and_attempt_listings(self, master):
        master.heartbeat({"tracker_name": "tr1", "host": "h1",
                          "task_statuses": []}, True, True, 0)
        assert master.get_active_trackers() == ["tr1"]
        assert master.get_blacklisted_trackers() == []
        jid = submit(master, "alice")
        assert master.get_attempt_ids(jid, "map", "running") == []


class TestCounterAccessor:
    def test_single_counter_bare_value(self, master, capsys):
        from tpumr.cli import main as cli_main
        jid = submit(master, "alice")
        host, port = master.address
        rc = cli_main(["-jt", f"{host}:{port}", "job", "-counter",
                       jid, "NoSuchGroup", "NoSuchName"])
        assert rc == 1
        assert "not found" in capsys.readouterr().err

    def test_single_counter_happy_path(self, master, capsys):
        from tpumr.cli import main as cli_main
        jid = submit(master, "alice")
        master.jobs[jid].counters.counter("MyGroup", "RECORDS").set_value(7)
        host, port = master.address
        rc = cli_main(["-jt", f"{host}:{port}", "job", "-counter",
                       jid, "MyGroup", "RECORDS"])
        assert rc == 0
        assert capsys.readouterr().out.strip() == "7"   # bare, scriptable
