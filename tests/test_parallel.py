"""Parallel-layer tests on the virtual 8-device CPU mesh: collectives,
device shuffle, sequence-parallel map, distributed K-Means step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpumr.parallel import (
    make_mesh, replicate, ring_pass, sequence_parallel_map, shard_over,
    shuffle_dense,
)
from tpumr.parallel.collectives import map_reduce
from tpumr.parallel.seqmap import ring_scan_map

NDEV = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= NDEV, "conftest must force 8 CPU devices"
    return make_mesh(NDEV)


def test_mesh_shapes():
    m = make_mesh(8)
    assert m.shape == {"data": 8}
    m2 = make_mesh(shape=(4, 2), axis_names=("data", "model"))
    assert m2.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        make_mesh(shape=(64,))


def test_shard_and_replicate(mesh):
    x = np.arange(32, dtype=np.float32).reshape(16, 2)
    xs = shard_over(mesh, x)
    assert xs.sharding.spec[0] == "data"
    np.testing.assert_array_equal(np.asarray(xs), x)
    c = replicate(mesh, np.ones(3))
    assert c.sharding.spec == jax.sharding.PartitionSpec()


def test_map_reduce_sums_over_mesh(mesh):
    x = np.arange(64, dtype=np.float32).reshape(64, 1)
    xs = shard_over(mesh, x)
    fn = map_reduce(mesh, lambda shard: {"s": jnp.sum(shard),
                                         "n": jnp.array(shard.shape[0])})
    out = fn(xs)
    assert float(out["s"]) == x.sum()
    assert int(out["n"]) == 64  # psum of per-shard counts


def test_shuffle_dense_repartitions_by_key(mesh):
    rng = np.random.default_rng(0)
    n, d = 512, 4
    values = rng.normal(size=(n, d)).astype(np.float32)
    keys = rng.integers(0, 1000, size=n).astype(np.int32)
    dest = (keys % NDEV).astype(np.int32)

    vs = shard_over(mesh, values)
    ds = shard_over(mesh, dest)
    ks = shard_over(mesh, keys)
    res = shuffle_dense(mesh, vs, ds, capacity=n // NDEV, keys=ks)
    assert int(res.overflow) == 0

    got_vals = np.asarray(res.values)
    got_valid = np.asarray(res.valid)
    got_keys = np.asarray(res.keys)
    # received arrays are globally sharded: device p holds slots
    # [p*ndev*cap, (p+1)*ndev*cap) — every valid record must have landed on
    # the device matching its key, and nothing may be lost
    cap = n // NDEV
    per_dev = NDEV * cap
    seen = []
    for p in range(NDEV):
        sl = slice(p * per_dev, (p + 1) * per_dev)
        vmask = got_valid[sl]
        kk = got_keys[sl][vmask]
        assert (kk % NDEV == p).all(), f"wrong-device records on {p}"
        seen.extend(kk.tolist())
    assert sorted(seen) == sorted(keys.tolist())
    # spot-check payloads travelled with their keys
    lookup = {}
    for i in range(n):
        lookup.setdefault(int(keys[i]), []).append(values[i])
    flat_valid = got_valid
    for idx in np.nonzero(flat_valid)[0][:50]:
        k = int(got_keys[idx])
        assert any(np.allclose(got_vals[idx], v) for v in lookup[k])


def test_shuffle_overflow_detected(mesh):
    n = 64
    values = np.ones((n, 2), np.float32)
    dest = np.zeros(n, np.int32)  # everything to device 0 — skew
    res = shuffle_dense(mesh, shard_over(mesh, values),
                        shard_over(mesh, dest), capacity=2)
    # each device could send only 2 of its 8 records to dev 0
    assert int(res.overflow) == n - NDEV * 2
    assert int(np.asarray(res.valid).sum()) == NDEV * 2


def test_sequence_parallel_map(mesh):
    x = np.arange(64, dtype=np.float32)
    fn = sequence_parallel_map(mesh, lambda s: s * 2 + 1)
    out = np.asarray(fn(shard_over(mesh, x)))
    np.testing.assert_array_equal(out, x * 2 + 1)


def test_ring_pass_rotates_shards(mesh):
    x = np.repeat(np.arange(NDEV, dtype=np.float32), 4)  # shard i holds i
    out = np.asarray(ring_pass(mesh)(shard_over(mesh, x)))
    expect = np.repeat((np.arange(NDEV) - 1) % NDEV, 4).astype(np.float32)
    np.testing.assert_array_equal(out, expect)


def test_ring_scan_folds_entire_axis(mesh):
    """After n hops of the ring every chip's state has seen every shard."""
    x = np.arange(64, dtype=np.float32)
    init = np.zeros(64, np.float32)  # per-chip state, sharded (8 each)
    fn = ring_scan_map(mesh, lambda state, visiting, hop: state + visiting.sum())
    out = np.asarray(fn(shard_over(mesh, init), shard_over(mesh, x)))
    np.testing.assert_allclose(out, np.full(64, x.sum()))


def test_distributed_kmeans_step_matches_single_device(mesh):
    from tpumr.ops.kmeans import make_distributed_step, _assign_and_partials_jax
    rng = np.random.default_rng(5)
    pts = rng.normal(size=(256, 4)).astype(np.float32)
    cents = rng.normal(size=(5, 4)).astype(np.float32)

    step = make_distributed_step(mesh)
    new_c, counts = step(shard_over(mesh, pts), replicate(mesh, cents))

    # single-device reference
    _a, sums, cnt = _assign_and_partials_jax(pts, cents)
    expect = np.where(np.asarray(cnt)[:, None] > 0,
                      np.asarray(sums) / np.maximum(np.asarray(cnt), 1)[:, None],
                      cents)
    np.testing.assert_allclose(np.asarray(new_c), expect, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(counts), np.asarray(cnt))


def test_multihost_spec_and_single_host_noop(monkeypatch):
    """Multi-host bring-up: conf keys beat env, nothing-configured is a
    single-host no-op whose global mesh covers the local devices."""
    import jax

    from tpumr.mapred.jobconf import JobConf
    from tpumr.parallel import multihost

    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
    assert multihost.distributed_spec(None) is None

    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "envhost:1234")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "2")
    spec = multihost.distributed_spec(None)
    assert spec == {"coordinator_address": "envhost:1234",
                    "num_processes": 4, "process_id": 2}

    conf = JobConf()
    conf.set("tpumr.distributed.coordinator", "confhost:9")
    conf.set("tpumr.distributed.num.processes", 8)
    spec = multihost.distributed_spec(conf)
    assert spec["coordinator_address"] == "confhost:9"   # conf wins
    assert spec["num_processes"] == 8
    assert spec["process_id"] == 2                       # env fallback

    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS")
    monkeypatch.delenv("JAX_NUM_PROCESSES")
    monkeypatch.delenv("JAX_PROCESS_ID")
    assert multihost.ensure_initialized(None) is False   # no-op path
    mesh = multihost.global_mesh(None)
    assert mesh.devices.size == len(jax.devices())
    assert multihost.process_info() == (0, 1)


class TestPersistentCompilationCache:
    def test_conf_key_lands_in_jax_config(self, tmp_path, monkeypatch):
        import jax

        from tpumr.mapred.jobconf import JobConf
        from tpumr.parallel import jaxruntime
        jaxruntime._reset_for_tests()
        prev = jax.config.jax_compilation_cache_dir
        try:
            conf = JobConf()
            conf.set("tpumr.jax.cache.dir", str(tmp_path / "jc"))
            got = jaxruntime.configure_persistent_cache(conf)
            assert got == str(tmp_path / "jc")
            assert jax.config.jax_compilation_cache_dir == got
            # idempotent: second caller (different conf) is a no-op
            other = JobConf()
            other.set("tpumr.jax.cache.dir", str(tmp_path / "other"))
            assert jaxruntime.configure_persistent_cache(other) == got
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)
            jaxruntime._reset_for_tests()

    def test_disabled_with_none(self, monkeypatch):
        import jax

        from tpumr.mapred.jobconf import JobConf
        from tpumr.parallel import jaxruntime
        jaxruntime._reset_for_tests()
        prev = jax.config.jax_compilation_cache_dir
        try:
            conf = JobConf()
            conf.set("tpumr.jax.cache.dir", "none")
            assert jaxruntime.configure_persistent_cache(conf) is None
            assert jax.config.jax_compilation_cache_dir == prev
        finally:
            jaxruntime._reset_for_tests()

    def test_cache_populates_and_hits_across_processes(self, tmp_path):
        """Two fresh processes share compiles through the cache dir —
        process 1 populates entries, process 2 must HIT (adds none).
        Deterministic entry-count assertions, no wall-clock ratios."""
        import os
        import subprocess
        import sys
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        prog = (
            "import sys\n"
            "sys.path.insert(0, %r)\n"
            "from tpumr.mapred.jobconf import JobConf\n"
            "from tpumr.parallel.jaxruntime import "
            "configure_persistent_cache\n"
            "conf = JobConf()\n"
            "conf.set('tpumr.jax.cache.dir', %r)\n"
            "conf.set('tpumr.jax.cache.min.compile.secs', 0.0)\n"
            "configure_persistent_cache(conf)\n"
            "import jax, jax.numpy as jnp\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "f = jax.jit(lambda x: jnp.sort(x * 2 + 1, axis=0))\n"
            "f(jnp.zeros((4096, 8))).block_until_ready()\n"
        ) % (repo_root, str(tmp_path / "xc"))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        entries = []
        for _ in range(2):
            out = subprocess.run([sys.executable, "-c", prog], env=env,
                                 capture_output=True, text=True, timeout=120)
            assert out.returncode == 0, out.stderr
            entries.append(sorted(os.listdir(tmp_path / "xc")))
        assert entries[0], "cache dir never populated"
        # process 2 compiled nothing new — it loaded process 1's entries
        assert entries[1] == entries[0], (entries[0], entries[1])
