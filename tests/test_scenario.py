"""Scenario lab (tpumr/scale/scenario.py) + master brownout
(tpumr/mapred/brownout.py): spec validation, deterministic trace
planning, per-class windowed SLO verdicts, the brownout step-up/step-
down state machine, the tracker-churn chaos seams, and two end-to-end
mixes (acceptance: churn completes every job with adoption counters
moving; overload engages the brownout, interactive recovers WHILE it
is active, and it fully steps down after the pressure clears)."""

import json
import os
import time
import types

import pytest

from tpumr.mapred.brownout import LEVELS, MAX_LEVEL, BrownoutController
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.jobtracker import JobMaster
from tpumr.metrics.flightrec import FlightRecorder
from tpumr.metrics.histogram import Histogram
from tpumr.scale import SimTracker
from tpumr.scale.scenario import (BUILTIN_SCENARIOS, ScenarioError,
                                  load_spec, plan, run_named,
                                  validate_spec)
from tpumr.utils import fi


def _spec(**over):
    base = {
        "name": "t",
        "classes": [{"name": "interactive", "jobs": 2, "maps": 2}],
    }
    base.update(over)
    return base


# ------------------------------------------------------------ specs


class TestSpecValidation:
    def test_minimal_spec_normalizes_with_defaults(self):
        out = validate_spec(_spec())
        assert out["fleet"]["trackers"] == 8
        assert out["master"]["expiry_ms"] == 60_000
        assert out["classes"][0]["priority"] == "NORMAL"
        assert out["classes"][0]["slo_assign_ms"] is None
        assert out["chaos"] == []

    def test_validate_is_idempotent(self):
        once = validate_spec(_spec())
        assert validate_spec(once) == once

    def test_unknown_keys_rejected_at_every_level(self):
        with pytest.raises(ScenarioError, match="unknown top-level"):
            validate_spec(_spec(typo=1))
        with pytest.raises(ScenarioError, match="unknown keys"):
            validate_spec(_spec(fleet={"trackerz": 4}))
        with pytest.raises(ScenarioError, match="unknown keys"):
            validate_spec(_spec(classes=[{"name": "a", "jbos": 2}]))

    def test_classes_required_and_named(self):
        with pytest.raises(ScenarioError, match="non-empty"):
            validate_spec({"name": "t", "classes": []})
        with pytest.raises(ScenarioError, match="identifier"):
            validate_spec(_spec(classes=[{"name": "no spaces!"}]))

    def test_bad_priority_and_negative_numbers_rejected(self):
        with pytest.raises(ScenarioError, match="priority"):
            validate_spec(_spec(
                classes=[{"name": "a", "priority": "URGENT"}]))
        with pytest.raises(ScenarioError, match="non-negative"):
            validate_spec(_spec(
                classes=[{"name": "a", "period_ms": -5}]))

    def test_chaos_kinds_and_fi_points_screened(self):
        with pytest.raises(ScenarioError, match="kind"):
            validate_spec(_spec(chaos=[{"kind": "meteor", "at_ms": 0}]))
        # fi points are bare seam names; the tpumr.fi. prefix is added
        # by the runner
        with pytest.raises(ScenarioError, match="bare seam"):
            validate_spec(_spec(chaos=[
                {"kind": "fi", "at_ms": 0,
                 "point": "tpumr.fi.task.slow", "probability": 0.5}]))
        with pytest.raises(ScenarioError, match="probability"):
            validate_spec(_spec(chaos=[
                {"kind": "fi", "at_ms": 0, "point": "task.slow",
                 "probability": 1.5}]))

    def test_builtins_all_validate(self):
        for name, spec in BUILTIN_SCENARIOS.items():
            out = validate_spec(spec)
            assert out["name"] == name
            assert out["classes"]


class TestPlanDeterminism:
    def test_same_seed_same_plan(self):
        for name in BUILTIN_SCENARIOS:
            spec = dict(BUILTIN_SCENARIOS[name], seed=1337)
            assert plan(spec) == plan(spec), name

    def test_plan_is_time_sorted_and_jitter_is_seeded(self):
        spec = _spec(classes=[{"name": "a", "jobs": 8, "maps": 1,
                               "period_ms": 100, "jitter_ms": 500}])
        p1 = plan(dict(spec, seed=1))
        assert [e["t_s"] for e in p1] == sorted(e["t_s"] for e in p1)
        assert p1 != plan(dict(spec, seed=2))

    def test_default_chaos_targets_drawn_from_seed(self):
        spec = _spec(chaos=[{"kind": "tracker_crash", "at_ms": 100,
                             "count": 2}])
        crash = [e for e in plan(dict(spec, seed=3))
                 if e["kind"] == "tracker_crash"]
        assert len(crash) == 1 and len(crash[0]["targets"]) == 2
        assert crash == [e for e in plan(dict(spec, seed=3))
                         if e["kind"] == "tracker_crash"]


class TestTomlSpecs:
    def _toml(self):
        try:
            import tomllib  # noqa: F401
        except ImportError:
            pytest.importorskip(
                "tomli", reason="TOML specs need py3.11+ or tomli")

    def test_load_spec_from_scenario_dir(self, tmp_path):
        self._toml()
        (tmp_path / "mini.toml").write_text(
            'seed = 9\n'
            '[fleet]\ntrackers = 3\n'
            '[[classes]]\nname = "quick"\njobs = 1\nmaps = 2\n'
            'slo_assign_ms = 5000\n')
        spec = load_spec("mini", scenario_dir=str(tmp_path))
        assert spec["name"] == "mini" and spec["seed"] == 9
        assert spec["classes"][0]["slo_assign_ms"] == 5000

    def test_bad_toml_is_a_scenario_error(self, tmp_path):
        self._toml()
        (tmp_path / "broken.toml").write_text("= not toml =")
        with pytest.raises(ScenarioError, match="bad TOML"):
            load_spec("broken", scenario_dir=str(tmp_path))

    def test_unknown_name_lists_builtins(self):
        with pytest.raises(ScenarioError, match="churn_storm"):
            load_spec("no_such_mix")


# ------------------------------------------------------------ brownout


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now

    def tick(self, s=1.0):
        self.now += s


def _ctrl(**over):
    clock = FakeClock()
    kw = dict(engage_ticks=3, release_ticks=2, dwell_s=5.0,
              cadence_factor=3.0, clock=clock)
    kw.update(over)
    return BrownoutController(**kw), clock


class TestBrownoutStateMachine:
    def test_engages_only_after_consecutive_pressure(self):
        b, clock = _ctrl()
        for _ in range(2):
            b.on_tick(True)
            clock.tick()
        assert b.level == 0
        b.on_tick(True)
        assert b.level == 1 and b.step_ups == 1

    def test_clear_tick_resets_the_run(self):
        b, clock = _ctrl()
        b.on_tick(True); clock.tick()
        b.on_tick(True); clock.tick()
        b.on_tick(False); clock.tick()   # run broken
        b.on_tick(True); clock.tick()
        b.on_tick(True); clock.tick()
        assert b.level == 0

    def test_dwell_rate_limits_step_ups(self):
        b, clock = _ctrl(dwell_s=10.0)
        for _ in range(3):
            b.on_tick(True); clock.tick()
        assert b.level == 1
        for _ in range(5):               # pressure continues, < dwell
            b.on_tick(True); clock.tick()
        assert b.level == 1
        clock.tick(10.0)
        for _ in range(3):
            b.on_tick(True); clock.tick()
        assert b.level == 2

    def test_release_steps_down_one_level_per_dwell(self):
        b, clock = _ctrl(dwell_s=1.0)
        for _ in range(3):
            b.on_tick(True); clock.tick(2.0)
        for _ in range(3):
            b.on_tick(True); clock.tick(2.0)
        assert b.level == 2
        downs = 0
        for _ in range(10):
            b.on_tick(False); clock.tick(2.0)
            downs = max(downs, b.step_downs)
            if b.level == 0:
                break
        assert b.level == 0 and b.step_downs == 2
        # transitions journaled (old, new) with the fake clock's stamps
        trans = [(t[1], t[2]) for t in b.transitions]
        assert trans == [(0, 1), (1, 2), (2, 1), (1, 0)]

    def test_caps_at_max_level(self):
        b, clock = _ctrl(dwell_s=0.0)
        for _ in range(MAX_LEVEL * 3 + 9):
            b.on_tick(True); clock.tick()
        assert b.level == MAX_LEVEL == len(LEVELS)

    def test_shed_ranking_is_graceful(self):
        # the ranked steps: trace sampling first, cadence second,
        # speculation + history I/O last — never the reverse
        b, _ = _ctrl()
        assert not b.sheds("trace")
        b._change(1, 0.0)
        assert b.sheds("trace") and not b.sheds("cadence")
        b._change(2, 0.0)
        assert b.sheds("cadence") and not b.sheds("speculation")
        b._change(3, 0.0)
        assert b.sheds("speculation") and b.sheds("history") \
            and b.sheds("trace")

    def test_stretch_interval_only_while_shedding_cadence(self):
        b, _ = _ctrl(cadence_factor=3.0)
        assert b.stretch_interval(0.1, 1.0) == pytest.approx(0.1)
        b._change(2, 0.0)
        assert b.stretch_interval(0.1, 1.0) == pytest.approx(0.3)
        # capped at the instructed max...
        assert b.stretch_interval(0.5, 1.0) == pytest.approx(1.0)
        # ...but never stretched BELOW the current interval when the
        # configured max is smaller than it
        assert b.stretch_interval(0.5, 0.2) == pytest.approx(0.5)

    def test_from_conf_disabled_by_default(self):
        conf = JobConf()
        assert BrownoutController.from_conf(conf) is None
        conf.set("tpumr.brownout.enabled", True)
        conf.set("tpumr.brownout.engage.ticks", 7)
        b = BrownoutController.from_conf(conf)
        assert b is not None and b.engage_ticks == 7

    def test_snapshot_shape(self):
        b, clock = _ctrl(dwell_s=0.0)
        for _ in range(3):
            b.on_tick(True); clock.tick()
        snap = b.snapshot()
        assert snap["level"] == 1 and snap["step_ups"] == 1
        assert snap["sheds"] == ["trace"]
        assert snap["transitions"][-1]["to"] == 1


# ------------------------------------------------------------ per-class fold


def _recorder(tmp_path, conf=None):
    master = types.SimpleNamespace(
        _hb_seconds=Histogram("heartbeat_seconds"),
        _hb_lag=Histogram("heartbeat_lag_seconds"),
        _class_hists={}, _mreg=None, brownout=None,
        scenario_name="unit")
    rec = FlightRecorder(master, None, slo_ms=250, cooldown_ms=0,
                         incident_dir=str(tmp_path), conf=conf)
    return master, rec


class TestPerClassWindows:
    def test_fold_windows_deltas_not_cumulative(self, tmp_path):
        conf = JobConf()
        conf.set("tpumr.scenario.slo.web.assign.ms", 100)
        master, rec = _recorder(tmp_path, conf)
        h = Histogram("class_assign_seconds|class=web")
        master._class_hists[("assign", "web")] = h
        h.observe(0.5)                       # breach (slo 100ms)
        rows = rec._fold_classes()
        assert rows == [("web", "assign", pytest.approx(rows[0][2]),
                         0.1, True)]
        assert rows[0][2] > 0.1
        st = rec._class_state["web"]
        assert st["assign_windows"] == 1
        assert st["assign_breach_windows"] == 1
        assert st["assign_ok"] is False
        # next window: only NEW observations count — fast ones now
        for _ in range(50):
            h.observe(0.01)
        rows = rec._fold_classes()
        assert rows[0][4] is False           # windowed p99 recovered
        assert rec._class_state["web"]["assign_ok"] is True
        # an empty window leaves the verdict state untouched
        assert rec._fold_classes() == []
        assert rec._class_state["web"]["assign_windows"] == 2

    def test_class_without_slo_observed_never_judged(self, tmp_path):
        master, rec = _recorder(tmp_path, JobConf())
        h = Histogram("class_complete_seconds|class=bulk")
        master._class_hists[("complete", "bulk")] = h
        h.observe(99.0)
        rec._fold_classes()
        report = rec.class_report()
        assert report["bulk"]["complete"]["ok"] is None
        assert report["bulk"]["pass"] is True

    def test_class_report_fails_breaching_class_only(self, tmp_path):
        conf = JobConf()
        conf.set("tpumr.scenario.slo.web.assign.ms", 100)
        conf.set("tpumr.scenario.slo.bulk.complete.ms", 60_000)
        master, rec = _recorder(tmp_path, conf)
        web = Histogram("a"); bulk = Histogram("b")
        master._class_hists[("assign", "web")] = web
        master._class_hists[("complete", "bulk")] = bulk
        web.observe(2.0); bulk.observe(1.0)
        rec._fold_classes()
        report = rec.class_report()
        assert report["web"]["pass"] is False
        assert report["bulk"]["pass"] is True

    def test_window_history_records_level_and_verdict_bits(
            self, tmp_path):
        conf = JobConf()
        conf.set("tpumr.scenario.slo.web.assign.ms", 100)
        master, rec = _recorder(tmp_path, conf)
        h = Histogram("x")
        master._class_hists[("assign", "web")] = h
        h.observe(0.5)
        # the window record is the subject here, not the bundle (the
        # stub master has no metrics system to snapshot)
        rec.write_incident = lambda breaches: None
        rec._tick()
        hist = rec.window_history()
        assert len(hist) == 1
        assert hist[0]["classes"]["web"]["assign_ok"] is False
        assert hist[0]["brownout_level"] == 0


# ------------------------------------------------------------ chaos seams


def _fi_conf(**keys):
    conf = JobConf()
    conf.set("tpumr.fi.seed", 42)
    for k, v in keys.items():
        conf.set(k, v)
    return conf


class TestTrackerCrashSeam:
    def setup_method(self):
        fi.reset()

    def teardown_method(self):
        fi.reset()

    def test_seam_fires_and_hard_kills_mid_beat(self):
        master = JobMaster(JobConf()).start()
        try:
            host, port = master.address
            conf = _fi_conf(**{
                "tpumr.fi.tracker.crash.probability": 1.0,
                "tpumr.fi.tracker.crash.max.failures": 1})
            t = SimTracker("doomed", host, port, fi_conf=conf)
            try:
                assert t.heartbeat_begin() is False
                assert t.crashed and t.stopped
                assert fi.fired("tracker.crash") == 1
                # capped: a fresh tracker under the same conf survives
                t2 = SimTracker("safe", host, port, fi_conf=conf)
                try:
                    assert t2.heartbeat_begin() is True
                    t2.heartbeat_finish()
                    assert not t2.crashed
                finally:
                    t2.close()
            finally:
                t.close()
        finally:
            master.stop()

    def test_targeted_seam_kills_only_its_slot(self):
        master = JobMaster(JobConf()).start()
        try:
            host, port = master.address
            conf = _fi_conf(**{
                "tpumr.fi.tracker.crash.t3.probability": 1.0})
            bystander = SimTracker("t2", host, port, index=2,
                                   fi_conf=conf)
            target = SimTracker("t3", host, port, index=3,
                                fi_conf=conf)
            try:
                assert bystander.heartbeat_begin() is True
                bystander.heartbeat_finish()
                assert target.heartbeat_begin() is False
                assert target.crashed and not bystander.crashed
            finally:
                bystander.close()
                target.close()
        finally:
            master.stop()


class TestColdReRegistration:
    def test_known_tracker_initial_contact_requeues_and_counts(self):
        """A tracker process that dies and comes back under its old
        name FASTER than the expiry sweep: the master must swap in the
        fresh registration, drop the stale replay-cache entry, and
        requeue the old incarnation's work — not feed the new process
        the dead one's actions."""
        conf = JobConf()
        conf.set("tpumr.heartbeat.interval.ms", 50)
        master = JobMaster(conf).start()
        host, port = master.address
        old = SimTracker("phoenix", host, port)
        try:
            old.heartbeat_once()
            assert "phoenix" in master.trackers
            # process dies silently...
            old.crash()
            # ...and the replacement registers under the same name
            # before any eviction sweep notices
            new = SimTracker("phoenix", host, port)
            try:
                new.heartbeat_once()
                jt = master.metrics.snapshot()["jobtracker"]
                assert jt.get("trackers_restarted", 0) == 1
                assert jt.get("trackers_adopted", 0) == 0
                # the new incarnation keeps beating normally (its
                # replay cache entry is its own, not the dead one's)
                new.heartbeat_once()
                assert new.heartbeats == 2
            finally:
                new.close()
        finally:
            old.close()
            master.stop()


# ------------------------------------------------------------ dfs specs


def _dfs_spec(**over):
    base = _spec(dfs={"datanodes": 3, "clients": 2, "files": 2,
                      "file_kb": 16})
    base.update(over)
    return base


class TestDFSSpecValidation:
    def test_dfs_table_normalizes_with_defaults(self):
        out = validate_spec(_dfs_spec())
        assert out["dfs"]["datanodes"] == 3
        assert out["dfs"]["replication_interval_ms"] == 200
        assert out["dfs"]["max_error_fraction"] == 0.02
        assert validate_spec(out) == out          # idempotent
        assert validate_spec(_spec())["dfs"] is None

    def test_storage_chaos_requires_dfs_table(self):
        for kind in ("dn_crash", "dn_partition", "nn_restart",
                     "block_corrupt"):
            with pytest.raises(ScenarioError, match="dfs"):
                validate_spec(_spec(
                    chaos=[{"kind": kind, "at_ms": 0}]))
            validate_spec(_dfs_spec(
                chaos=[{"kind": kind, "at_ms": 0}]))

    def test_out_of_range_targets_rejected(self):
        with pytest.raises(ScenarioError, match="datanode indexes"):
            validate_spec(_dfs_spec(chaos=[
                {"kind": "dn_crash", "at_ms": 0, "targets": [3]}]))
        with pytest.raises(ScenarioError, match="file_index"):
            validate_spec(_dfs_spec(chaos=[
                {"kind": "block_corrupt", "at_ms": 0,
                 "file_index": 2}]))

    def test_too_few_datanodes_rejected(self):
        # the seeded working set writes at replication=2
        with pytest.raises(ScenarioError, match="datanodes"):
            validate_spec(_spec(dfs={"datanodes": 1}))


class TestDFSPlanDeterminism:
    def test_dn_crash_targets_and_corrupt_file_drawn_from_seed(self):
        spec = _dfs_spec(chaos=[
            {"kind": "dn_crash", "at_ms": 100, "count": 2},
            {"kind": "block_corrupt", "at_ms": 200},
            {"kind": "nn_restart", "at_ms": 300, "outage_ms": 250},
            {"kind": "dn_partition", "at_ms": 400,
             "duration_ms": 1500},
        ])
        p1 = plan(dict(spec, seed=7))
        assert p1 == plan(dict(spec, seed=7))
        rows = {e["kind"]: e for e in p1 if e["kind"] != "submit"}
        assert len(rows["dn_crash"]["targets"]) == 2
        assert all(0 <= t < 3 for t in rows["dn_crash"]["targets"])
        assert 0 <= rows["block_corrupt"]["file_index"] < 2
        assert rows["nn_restart"]["outage_s"] == pytest.approx(0.25)
        assert rows["dn_partition"]["duration_s"] == pytest.approx(1.5)


# ------------------------------------------------------------ e2e mixes


class TestScenarioEndToEnd:
    def test_churn_mix_completes_everything_with_adoption(
            self, tmp_path):
        """Acceptance: trackers hard-killed mid-run, partitioned past
        the expiry, and crash-rejoined inside it — every workload still
        completes and the adoption/restart counters prove each rejoin
        path actually ran."""
        rep = run_named("churn_storm", seed=1337,
                        artifacts_dir=str(tmp_path))
        jobs = rep["jobs"]
        assert jobs["failed"] == 0 and jobs["unfinished"] == 0
        assert jobs["succeeded"] == jobs["submitted"] > 0
        chaos = rep["chaos"]
        assert chaos["trackers_crashed"] >= 2
        assert chaos["trackers_respawned"] >= 2
        assert chaos["trackers_adopted"] >= 1
        assert chaos["fi_fired"]["tracker.crash"] >= 1
        assert rep["pass"] is True
        # the replay plan is the determinism surface: re-planning the
        # same (spec, seed) reproduces the exact schedule this run used
        assert rep["plan"] == plan(
            dict(BUILTIN_SCENARIOS["churn_storm"], seed=1337))

    def test_overload_mix_brownout_engages_recovers_releases(
            self, tmp_path):
        """Acceptance: sustained master-side pressure engages the
        brownout; interactive-class SLO recovers WHILE the brownout is
        active (graceful degradation — batch slows, never the
        reverse); after the pressure clears it steps fully down."""
        rep = run_named("overload_brownout", seed=1337,
                        artifacts_dir=str(tmp_path))
        jobs = rep["jobs"]
        assert jobs["failed"] == 0 and jobs["unfinished"] == 0
        assert rep["brownout_max_level"] >= 1
        assert rep["brownout"]["level"] == 0          # fully released
        assert rep["brownout"]["step_downs"] >= 1
        hist = rep["window_history"]
        recovered_under_brownout = any(
            r["brownout_level"] > 0
            and (r["classes"].get("interactive") or {}).get(
                "assign_ok") is True
            for r in hist)
        assert recovered_under_brownout, \
            [(r["brownout_level"],
              (r["classes"].get("interactive") or {}).get("assign_ok"))
             for r in hist]
        assert rep["verdicts"]["interactive"]["pass"] is True
        # an incident bundle was written and carries the workload
        # context: scenario name, brownout state, per-class breakdown
        assert rep["incidents"], "overload must write an incident"
        inc_dir = os.path.join(str(tmp_path), "incidents")
        with open(os.path.join(inc_dir, rep["incidents"][0])) as f:
            doc = json.load(f)
        assert doc["workload"]["scenario"] == "overload_brownout"
        assert "classes" in doc["workload"]
        assert "level" in doc["workload"]["brownout"]

    def test_dfs_churn_mix_heals_and_readers_never_see_rot(
            self, tmp_path):
        """Acceptance: a replica corrupted under live verified reads,
        a datanode hard-killed with a cold rejoin, and a heartbeat
        partition — the MapReduce classes all complete, the verifying
        DFS fleet sees ZERO corrupt reads, and the cluster converges
        to a clean fsck."""
        rep = run_named("dfs_churn_storm", seed=20260804,
                        artifacts_dir=str(tmp_path))
        jobs = rep["jobs"]
        assert jobs["failed"] == 0 and jobs["unfinished"] == 0
        dfs = rep["dfs"]
        assert dfs["ops"] > 0
        assert dfs["corrupt_reads"] == 0
        assert dfs["heal"]["healed"] is True
        assert dfs["pass"] is True
        chaos = rep["chaos"]
        assert chaos["datanodes_killed"] == 1
        assert chaos["fi_fired"]["dn.partition"] == 1
        # the corrupted block's targeted seam fired exactly once
        corrupt = [r for r in rep["chaos_log"]
                   if r["kind"] == "block_corrupt"][0]
        assert corrupt["block_id"] is not None
        assert chaos["fi_fired"][
            f"dn.read.corrupt.b{corrupt['block_id']}"] == 1
        assert rep["pass"] is True
        assert rep["plan"] == plan(
            dict(BUILTIN_SCENARIOS["dfs_churn_storm"], seed=20260804))

    def test_dfs_nn_failover_clients_ride_the_outage(self, tmp_path):
        """Acceptance: NameNode SIGKILLed mid-mix and rebound on the
        same port — editlog replay + safemode exit are timed into the
        chaos log, the fleet's error budget holds (safemode refusals
        budgeted separately), and every MapReduce job completes."""
        rep = run_named("dfs_nn_failover", seed=20260804,
                        artifacts_dir=str(tmp_path))
        jobs = rep["jobs"]
        assert jobs["failed"] == 0 and jobs["unfinished"] == 0
        assert rep["chaos"]["nn_restarts"] == 1
        restart = [r for r in rep["chaos_log"]
                   if r["kind"] == "nn_restart"][0]
        assert restart["safemode_exited"] is True
        assert restart["safemode_exit_s"] < 10.0
        dfs = rep["dfs"]
        assert dfs["corrupt_reads"] == 0
        assert dfs["verdicts"]["errors_ok"] is True
        assert dfs["heal"]["healed"] is True
        assert rep["pass"] is True
