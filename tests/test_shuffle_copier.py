"""The shuffle copy phase: parallel fetchers, chunked streaming, RAM budget
with disk spill (≈ ReduceCopier/ShuffleRamManager, ReduceTask.java:659/:1080,
chunk serving ≈ MapOutputServlet TaskTracker.java:4050)."""

import io
import threading
import time

import pytest

from tpumr.io import ifile
from tpumr.io.compress import get_codec
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.shuffle_copier import (DiskSegment, LocalSegmentSource,
                                         MemorySegment, ShuffleCopier,
                                         ShuffleRamManager)


def make_spill(records, codec="zlib", partitions=1):
    """Write one spill file (all records into partition 0)."""
    buf = io.BytesIO()
    w = ifile.Writer(buf, codec=codec)
    for p in range(partitions):
        w.start_partition()
        if p == 0:
            for k, v in records:
                w.append_raw(k, v)
        w.end_partition()
    index = w.close()
    return buf.getvalue(), index


class SpillChunkSource:
    """ChunkFetch over in-memory spill files — mirrors the tracker's
    get_map_output_chunk contract, with instrumentation."""

    def __init__(self, spills, chunk_cap=1 << 20):
        self.spills = spills          # list of (file_bytes, index)
        self.chunk_bytes = chunk_cap  # duck-types RemoteChunkSource
        self.calls = 0
        self.in_flight = 0
        self.max_in_flight = 0
        self.fail_first_for = set()   # map indices that fail once
        self._failed = set()
        self._lock = threading.Lock()

    def __call__(self, map_index, partition, offset):
        with self._lock:
            self.calls += 1
            self.in_flight += 1
            self.max_in_flight = max(self.max_in_flight, self.in_flight)
            if map_index in self.fail_first_for and \
                    map_index not in self._failed:
                self._failed.add(map_index)
                self.in_flight -= 1
                raise ConnectionError("synthetic fetch failure")
        try:
            time.sleep(0.01)  # hold the slot so concurrency is observable
            data, index = self.spills[map_index]
            off, raw_len, part_len = index["partitions"][partition]
            payload = data[off + 4: off + part_len]
            return {"data": payload[offset: offset + self.chunk_bytes],
                    "total": len(payload), "raw": raw_len,
                    "codec": index.get("codec", "none")}
        finally:
            with self._lock:
                self.in_flight -= 1


def records_for(n, tag=b"m"):
    return [(b"%s-%06d" % (tag, i), b"v" * 10) for i in range(n)]


def conf_with(**kv):
    conf = JobConf()
    for k, v in kv.items():
        conf.set(k.replace("_", "."), v)
    return conf


class TestChunkedSegmentIO:
    @pytest.mark.parametrize("codec", ["none", "zlib", "bzip2", "lzma",
                                       "tlz"])
    def test_roundtrip_tiny_chunks(self, codec):
        recs = records_for(500)
        data, index = make_spill(recs, codec=codec)
        off, raw, plen = index["partitions"][0]
        payload = data[off + 4: off + plen]
        # 7-byte chunks guarantee vints and records split across chunks
        chunks = [payload[i:i + 7] for i in range(0, len(payload), 7)]
        got = list(ifile.iter_chunked_segment(chunks, codec))
        assert got == recs

    def test_truncated_stream_raises(self):
        recs = records_for(50)
        data, index = make_spill(recs, codec="none")
        off, raw, plen = index["partitions"][0]
        payload = data[off + 4: off + plen]
        with pytest.raises(EOFError):
            list(ifile.iter_chunked_segment([payload[:len(payload) // 2]],
                                            "none"))


class TestRamManager:
    def test_reserve_release(self):
        ram = ShuffleRamManager(1000, max_single_frac=0.5)
        assert ram.try_reserve(400)
        assert ram.try_reserve(500)
        assert not ram.try_reserve(200)   # budget full
        ram.release(400)
        assert ram.try_reserve(200)

    def test_oversized_segment_refused(self):
        ram = ShuffleRamManager(1000, max_single_frac=0.25)
        assert not ram.try_reserve(251)   # > max_single even though < budget
        assert ram.try_reserve(250)


class TestShuffleCopier:
    def test_parallel_copies_honored(self, tmp_path):
        spills = [make_spill(records_for(200, b"m%d" % i)) for i in range(8)]
        src = SpillChunkSource(spills)
        conf = conf_with(tpumr_shuffle_parallel_copies=4)
        copier = ShuffleCopier(conf, src, 8, 0, str(tmp_path))
        segs = copier.copy_all()
        assert len(segs) == 8
        # the dead key is live: fetches genuinely overlap
        assert src.max_in_flight > 1
        assert copier.parallel == 4
        merged = ifile.merge_sorted(segs, lambda k: k)
        assert sum(1 for _ in merged) == 8 * 200

    def test_sequential_when_one_copy(self, tmp_path):
        spills = [make_spill(records_for(50, b"m%d" % i)) for i in range(4)]
        src = SpillChunkSource(spills)
        conf = conf_with(tpumr_shuffle_parallel_copies=1)
        segs = ShuffleCopier(conf, src, 4, 0, str(tmp_path)).copy_all()
        assert len(segs) == 4 and src.max_in_flight == 1

    def test_chunked_transfer(self, tmp_path):
        recs = records_for(5000)
        spills = [make_spill(recs, codec="none")]
        src = SpillChunkSource(spills, chunk_cap=1024)  # force many chunks
        copier = ShuffleCopier(JobConf(), src, 1, 0, str(tmp_path))
        segs = copier.copy_all()
        assert src.calls > 10                      # streamed, not one-shot
        assert list(segs[0]) == recs

    def test_oversized_segment_spills_to_disk(self, tmp_path):
        big = records_for(20000)                   # raw ~0.5 MB
        small = records_for(10, b"s")
        spills = [make_spill(big), make_spill(small)]
        src = SpillChunkSource(spills)
        conf = conf_with(tpumr_shuffle_ram_mb=0.1)  # ~73 KB budget
        copier = ShuffleCopier(conf, src, 2, 0, str(tmp_path))
        segs = copier.copy_all()
        assert copier.spilled_to_disk >= 1
        assert isinstance(segs[0], DiskSegment)    # big one went to disk
        assert isinstance(segs[1], MemorySegment)  # small one fit
        assert list(segs[0]) == big and list(segs[1]) == small
        # closing deletes the spill and releases the budget
        import os
        path = segs[0].path
        assert os.path.exists(path)
        for s in segs:
            s.close()
        assert not os.path.exists(path)
        assert copier.ram.used == 0

    def test_ram_budget_never_exceeded(self, tmp_path):
        spills = [make_spill(records_for(3000, b"m%d" % i))
                  for i in range(6)]
        src = SpillChunkSource(spills)
        conf = conf_with(tpumr_shuffle_ram_mb=0.2)
        copier = ShuffleCopier(conf, src, 6, 0, str(tmp_path))
        segs = copier.copy_all()
        assert copier.ram.used <= copier.ram.budget
        total = sum(1 for s in segs for _ in s)
        assert total == 6 * 3000

    def test_retry_recovers_transient_failure(self, tmp_path):
        spills = [make_spill(records_for(100, b"m%d" % i)) for i in range(3)]
        src = SpillChunkSource(spills)
        src.fail_first_for = {1}
        conf = conf_with()
        conf.set("tpumr.shuffle.copy.backoff.ms", 1)
        segs = ShuffleCopier(conf, src, 3, 0, str(tmp_path)).copy_all()
        assert len(segs) == 3

    def test_permanent_failure_raises(self, tmp_path):
        class DeadSource:
            chunk_bytes = 1 << 20

            def __call__(self, m, p, o):
                raise ConnectionError("gone")

        conf = conf_with()
        conf.set("tpumr.shuffle.copy.retries", 1)
        conf.set("tpumr.shuffle.copy.backoff.ms", 1)
        with pytest.raises(RuntimeError, match="failed after 2 attempts"):
            ShuffleCopier(conf, DeadSource(), 2, 0, str(tmp_path)).copy_all()


class TestLocalSegmentSource:
    def test_lazy_spill_views(self, tmp_path):
        recs = records_for(300)
        data, index = make_spill(recs, codec="zlib")
        p = tmp_path / "spill0"
        p.write_bytes(data)
        src = LocalSegmentSource([(str(p), index), ("", {})])
        segs = src.segments(0)
        assert len(segs) == 1          # empty map output skipped
        assert list(segs[0]) == recs
        segs[0].close()
        assert p.exists()              # view never deletes the spill


class TestEndToEnd:
    def test_distributed_job_with_spill_and_tiny_chunks(self):
        """A real mini-cluster job forced through the disk-spill +
        multi-chunk path must produce correct output."""
        from tpumr.fs import FileSystem, get_filesystem
        from tpumr.mapred.job_client import JobClient
        from tpumr.mapred.mini_cluster import MiniMRCluster

        base = JobConf()
        base.set("tpumr.shuffle.chunk.bytes", 65536)  # floor of the clamp
        base.set("tpumr.shuffle.ram.mb", 0.05)        # everything spills
        with MiniMRCluster(num_trackers=2, conf=base) as c:
            fs = get_filesystem("mem:///")
            fs.write_bytes("/sc/in.txt",
                           b"".join(b"w%03d x\n" % (i % 97)
                                    for i in range(20000)))
            conf = c.create_job_conf()
            conf.set_input_paths("mem:///sc/in.txt")
            conf.set_output_path("mem:///sc/out")
            conf.set("mapred.mapper.class", "tpumr.mapred.lib.TokenCountMapper")
            conf.set("mapred.reducer.class",
                     "tpumr.examples.basic.LongSumReducer")
            conf.set_num_reduce_tasks(2)
            conf.set("mapred.map.tasks", 4)
            conf.set("mapred.min.split.size", 1)
            result = JobClient(conf).run_job(conf)
            assert result.successful
            out = b"".join(fs.read_bytes(st.path)
                           for st in fs.list_status("/sc/out")
                           if "part-" in str(st.path))
            counts = dict(line.split(b"\t") for line in out.splitlines())
            assert counts[b"x"] == b"20000"
            assert counts[b"w000"] == b"207"  # 20000/97 → 207 occurrences
        FileSystem.clear_cache()

    def test_distributed_job_with_tlz_compressed_map_output(self):
        """Map-output compression through the native tlz codec across
        the full spill→serve→copy→merge path (the reference enables
        its JNI codecs exactly here: mapred.compress.map.output)."""
        from tpumr.fs import FileSystem, get_filesystem
        from tpumr.mapred.job_client import JobClient
        from tpumr.mapred.mini_cluster import MiniMRCluster

        base = JobConf()
        base.set("tpumr.shuffle.chunk.bytes", 65536)
        with MiniMRCluster(num_trackers=2, conf=base) as c:
            fs = get_filesystem("mem:///")
            fs.write_bytes("/tlz/in.txt",
                           b"".join(b"w%03d x\n" % (i % 53)
                                    for i in range(10000)))
            conf = c.create_job_conf()
            conf.set_input_paths("mem:///tlz/in.txt")
            conf.set_output_path("mem:///tlz/out")
            conf.set("mapred.mapper.class",
                     "tpumr.mapred.lib.TokenCountMapper")
            conf.set("mapred.reducer.class",
                     "tpumr.examples.basic.LongSumReducer")
            conf.set("mapred.compress.map.output", True)
            conf.set("mapred.map.output.compression.codec", "tlz")
            conf.set_num_reduce_tasks(2)
            conf.set("mapred.map.tasks", 4)
            conf.set("mapred.min.split.size", 1)
            result = JobClient(conf).run_job(conf)
            assert result.successful
            out = b"".join(fs.read_bytes(st.path)
                           for st in fs.list_status("/tlz/out")
                           if "part-" in str(st.path))
            counts = dict(line.split(b"\t") for line in out.splitlines())
            assert counts[b"x"] == b"10000"
        FileSystem.clear_cache()
