"""The shuffle merge engine: background in-memory merges under the RAM
budget (≈ ReduceTask's InMemFSMergeThread), bounded-fan-in multi-pass
merging honoring io.sort.factor (≈ Merger pass selection), the raw-key
merge fast paths, and the streaming combiner-at-merge."""

import heapq
import io
import os
import random
import time

import pytest

from tpumr.io import ifile
from tpumr.io import merger as merge_engine
from tpumr.mapred.api import (DeserializingComparator, RawComparator,
                              Reporter)
from tpumr.mapred.jobconf import JobConf
from tpumr.core.counters import TaskCounter
from tpumr.io.writable import deserialize, serialize
from tpumr.mapred.shuffle_copier import (DiskSegment, MemorySegment,
                                         ShuffleCopier, ShuffleMergeManager,
                                         ShuffleRamManager)


def rand_segments(n_segs, n_recs, seed=0, dup_keys=True):
    """Sorted segments with heavy key overlap and per-segment-unique
    values, so equal-key tiebreak order is observable."""
    rng = random.Random(seed)
    space = max(4, n_recs // 2) if dup_keys else n_recs * 100
    return [sorted((b"k%06d" % rng.randrange(space),
                    b"s%d-%04d" % (s, i))
                   for i in range(n_recs))
            for s in range(n_segs)]


def flat_merge(segs):
    """The seed's flat path: one heap merge with a key-fn closure."""
    sk = lambda k: k  # noqa: E731
    return list(heapq.merge(*segs, key=lambda kv: sk(kv[0])))


def make_spill(records, codec="none"):
    buf = io.BytesIO()
    w = ifile.Writer(buf, codec=codec)
    w.start_partition()
    for k, v in records:
        w.append_raw(k, v)
    w.end_partition()
    return buf.getvalue(), w.close()


class SpillChunkSource:
    """ChunkFetch over in-memory spill files."""

    chunk_bytes = 1 << 20

    def __init__(self, spills):
        self.spills = spills

    def __call__(self, map_index, partition, offset):
        data, index = self.spills[map_index]
        off, raw_len, part_len = index["partitions"][partition]
        payload = data[off + 4: off + part_len]
        return {"data": payload[offset: offset + self.chunk_bytes],
                "total": len(payload), "raw": raw_len,
                "codec": index.get("codec", "none")}


# ---------------------------------------------------------------- fast path


class TestRawFastPath:
    def test_identity_detection(self):
        assert ifile.is_raw_sort_key(None)
        assert ifile.is_raw_sort_key(lambda k: k)
        assert ifile.is_raw_sort_key(RawComparator().sort_key)
        # the deserializing comparator re-types keys: NOT raw
        assert not ifile.is_raw_sort_key(DeserializingComparator().sort_key)
        assert not ifile.is_raw_sort_key(lambda k: k[::-1])

    def test_two_way_merge_byte_identical(self):
        a, b = rand_segments(2, 500, seed=1)
        assert list(ifile.merge_sorted([a, b], lambda k: k)) == \
            flat_merge([a, b])

    @pytest.mark.parametrize("n", [1, 2, 3, 8])
    def test_kway_byte_identical_with_dup_keys(self, n):
        segs = rand_segments(n, 300, seed=n)
        assert list(ifile.merge_sorted(segs, lambda k: k)) == \
            flat_merge(segs)

    def test_empty_and_uneven_segments(self):
        segs = [[], [(b"a", b"1")], [], [(b"a", b"2"), (b"b", b"3")]]
        assert list(ifile.merge_sorted(segs, None)) == flat_merge(segs)
        assert list(ifile.merge_sorted([], None)) == []

    def test_inmem_merge_byte_identical(self):
        segs = rand_segments(6, 400, seed=3)
        assert ifile.merge_sorted_inmem(segs, lambda k: k) == \
            flat_merge(segs)

    def test_non_identity_sort_key_respected(self):
        # reversed-bytes order: the fast path must NOT kick in
        segs = [sorted(((b"ab", b"1"), (b"zx", b"2")),
                       key=lambda kv: kv[0][::-1]),
                sorted(((b"ba", b"3"), (b"xz", b"4")),
                       key=lambda kv: kv[0][::-1])]
        got = [k for k, _ in ifile.merge_sorted(segs, lambda k: k[::-1])]
        assert got == sorted(got, key=lambda k: k[::-1])
        got2 = ifile.merge_sorted_inmem(segs, lambda k: k[::-1])
        assert [k for k, _ in got2] == got


# ------------------------------------------------------------ bounded merge


class CloseTracking(list):
    closed = False

    def close(self):
        self.closed = True


class TestBoundedMerge:
    FACTOR = 4

    @pytest.mark.parametrize("n_segs", [1, 3, 4, 5, 12])
    def test_multipass_byte_identical_to_flat(self, n_segs, tmp_path):
        """Around the io.sort.factor boundaries (1, factor, factor+1,
        3x factor) the multi-pass output must be byte-identical to the
        flat merge — the contiguous-window pass selection preserves the
        segment-order tiebreak."""
        segs = rand_segments(n_segs, 200, seed=n_segs)
        bm = merge_engine.BoundedMerge(
            [list(s) for s in segs], lambda k: k, self.FACTOR,
            run_dir=str(tmp_path))
        got = list(bm)
        assert got == flat_merge(segs)
        assert bm.max_fan_in <= max(2, self.FACTOR)
        assert (bm.passes > 0) == (n_segs > self.FACTOR)
        bm.close()
        assert os.listdir(tmp_path) == []   # intermediate runs deleted

    def test_fan_in_never_exceeds_factor_wide(self, tmp_path):
        segs = rand_segments(33, 40, seed=7)
        bm = merge_engine.BoundedMerge(segs, None, 5,
                                       run_dir=str(tmp_path))
        assert list(bm) == flat_merge(segs)
        assert bm.max_fan_in <= 5 and bm.passes >= 7
        bm.close()

    def test_pass_counters_and_input_close(self, tmp_path):
        reporter = Reporter()
        segs = [CloseTracking(s) for s in rand_segments(9, 50)]
        bm = merge_engine.BoundedMerge(segs, None, 3,
                                       run_dir=str(tmp_path),
                                       reporter=reporter)
        list(bm)
        assert reporter.counters.value(
            TaskCounter.FRAMEWORK_GROUP,
            TaskCounter.MERGE_PASSES) == bm.passes > 0
        assert reporter.counters.value(
            TaskCounter.FRAMEWORK_GROUP,
            TaskCounter.MERGE_PASS_SEGMENTS) > 0
        # every pass-consumed input was closed promptly
        assert sum(1 for s in segs if s.closed) >= bm.passes
        bm.close()

    def test_streaming_run_decodes_as_ifile_segment(self, tmp_path):
        """write_run_streaming's padded-count patch must still decode
        as a standard single-partition IFile segment — including across
        its internal block-flush boundary."""
        recs = rand_segments(1, 5000, seed=11)[0]   # > one join block? no,
        run = merge_engine.write_run_streaming(iter(recs), str(tmp_path))
        assert list(run) == recs
        assert run.records == len(recs)
        # readable through the generic ifile partition reader too
        index = {"codec": "none",
                 "partitions": [(4, run.raw_length, run.length + 4)]}
        with open(run.path, "rb") as f:
            assert list(ifile.read_partition(f, index, 0)) == recs
        run.close()

    def test_padded_vint_roundtrip(self):
        from tpumr.io.writable import read_vint
        for n in (0, 1, 127, 128, 100000, 2**34 - 1):
            buf = io.BytesIO(merge_engine._padded_vint(n))
            assert read_vint(buf) == n
        with pytest.raises(ValueError):
            merge_engine._padded_vint(2**35)

    def test_write_run_format_matches_writer(self, tmp_path):
        """write_run's direct framing must stay byte-identical to
        ifile.Writer's single-partition output."""
        recs = rand_segments(1, 100, seed=9)[0]
        run = merge_engine.write_run(iter(recs), str(tmp_path),
                                     codec="zlib")
        assert list(run) == recs
        assert run.records == len(recs)
        data, index = make_spill(recs, codec="zlib")
        with open(run.path, "rb") as f:
            assert f.read() == data
        run.close()
        assert not os.path.exists(run.path)


# ------------------------------------------------------- background merges


def conf_for_copier(ram_mb, merge_enabled=True, combiner=None):
    conf = JobConf()
    conf.set_output_key_comparator_class(RawComparator)
    conf.set("tpumr.shuffle.ram.mb", ram_mb)
    conf.set("tpumr.shuffle.merge.enabled", merge_enabled)
    if combiner is not None:
        conf.set_combiner_class(combiner)
    return conf


class TestBackgroundMerge:
    def _spills(self, n_maps=30, n_recs=400):
        return [make_spill(rand_segments(1, n_recs, seed=m)[0])
                for m in range(n_maps)], n_maps * n_recs

    def test_wide_shuffle_merges_in_memory_and_releases_budget(
            self, tmp_path):
        """The acceptance shape: ≥30 maps, budget ≪ total bytes — at
        least one background merge runs, budget is observably released
        mid-copy (more segments land in memory than fit at once), and
        the merged stream equals the flat merge's content."""
        spills, total = self._spills(30)
        seg_raw = spills[0][1]["partitions"][0][1]
        budget_segs = 6
        ram_mb = seg_raw * (budget_segs + 0.2) / (0.70 * 1024 * 1024)
        reporter = Reporter()
        copier = ShuffleCopier(conf_for_copier(ram_mb),
                               SpillChunkSource(spills), 30, 0,
                               str(tmp_path), reporter)
        segs = copier.copy_all()
        assert copier.merger is not None
        assert copier.inmem_merges >= 1
        mem_placed = reporter.counters.value(
            TaskCounter.FRAMEWORK_GROUP,
            TaskCounter.REDUCE_SHUFFLE_SEGMENTS_MEM)
        # more in-memory placements than the budget can hold at once ⇒
        # reservations were released mid-copy and fetchers kept landing
        assert mem_placed > budget_segs
        assert reporter.counters.value(
            TaskCounter.FRAMEWORK_GROUP,
            TaskCounter.SHUFFLE_INMEM_MERGES) == copier.inmem_merges
        # returned streams: pre-merged runs + live segments, all sorted
        assert any(isinstance(s, merge_engine.DiskRun) for s in segs)
        bm = merge_engine.BoundedMerge(segs, None, 10,
                                       run_dir=str(tmp_path))
        got = list(bm)
        # content check against the ground truth (order of equal-key
        # values may differ from the flat path across merge batches —
        # same multiset, keys non-decreasing)
        expect = sorted(kv for data, idx in spills
                        for kv in self._read_spill(data, idx))
        assert sorted(got) == expect
        keys = [k for k, _ in got]
        assert keys == sorted(keys)
        assert len(got) == total
        bm.close()
        for s in segs:
            s.close()
        assert copier.ram.used == 0

    @staticmethod
    def _read_spill(data, index):
        off, raw_len, part_len = index["partitions"][0]
        return list(ifile.iter_chunked_segment(
            [data[off + 4: off + part_len]], index.get("codec", "none")))

    def test_disk_segments_drop_vs_merge_disabled(self, tmp_path):
        """The counter the ISSUE gates on: with the engine on, fewer
        segments fall to per-segment disk spills than the seed path."""
        spills, _ = self._spills(24)
        seg_raw = spills[0][1]["partitions"][0][1]
        ram_mb = seg_raw * 6.2 / (0.70 * 1024 * 1024)

        def run(enabled, sub):
            d = tmp_path / sub
            d.mkdir()
            reporter = Reporter()
            copier = ShuffleCopier(conf_for_copier(ram_mb, enabled),
                                   SpillChunkSource(spills), 24, 0,
                                   str(d), reporter)
            segs = copier.copy_all()
            disk = reporter.counters.value(
                TaskCounter.FRAMEWORK_GROUP,
                TaskCounter.REDUCE_SHUFFLE_SEGMENTS_DISK)
            for s in segs:
                s.close()
            return disk, copier

        disk_on, c_on = run(True, "on")
        disk_off, c_off = run(False, "off")
        assert c_off.merger is None and c_on.merger is not None
        assert disk_off > 0
        assert disk_on < disk_off
        assert c_on.inmem_merges >= 1

    def test_merge_error_fails_fast_not_per_fetch_timeout(self, tmp_path):
        """A combiner blowing up inside a background merge must kill the
        copy phase promptly: busy_or_pending flips false (fetchers stop
        burning the reserve-wait timeout), offers are refused, and
        finish() surfaces the stored error."""

        class BoomCombiner:
            def reduce(self, key, values, output, reporter):
                raise RuntimeError("boom at merge time")

            def close(self):
                pass

        spills = [make_spill(sorted(((serialize(f"k{i:03d}"), serialize(1))
                                     for i in range(60)),
                                    key=lambda kv: deserialize(kv[0])))
                  for _ in range(16)]
        seg_raw = spills[0][1]["partitions"][0][1]
        ram_mb = seg_raw * 6.2 / (0.70 * 1024 * 1024)
        conf = conf_for_copier(ram_mb, combiner=BoomCombiner)
        conf.set_class("mapred.output.key.comparator.class",
                       DeserializingComparator)
        copier = ShuffleCopier(conf, SpillChunkSource(spills), 16, 0,
                               str(tmp_path))
        import time
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="boom at merge time"):
            copier.copy_all()
        # 16 fetches each burning the 2s reserve-wait would take >> this
        assert time.monotonic() - t0 < 10
        assert not copier.merger.busy_or_pending()
        assert copier.ram.used == 0

    def test_combiner_runs_at_shuffle_merge_time(self, tmp_path):
        """Combiner correctness when it runs inside the background
        merge: aggregates are partial (per batch) but the grand totals
        must be exact, and combine counters must tick."""
        from tpumr.examples.basic import LongSumReducer
        n_maps, keys = 16, [f"w{i:02d}" for i in range(5)]
        spills = []
        for m in range(n_maps):
            recs = sorted(((serialize(k), serialize(1))
                           for k in keys for _ in range(3)),
                          key=lambda kv: deserialize(kv[0]))
            spills.append(make_spill(recs))
        seg_raw = spills[0][1]["partitions"][0][1]
        ram_mb = seg_raw * 6.2 / (0.70 * 1024 * 1024)
        conf = conf_for_copier(ram_mb, combiner=LongSumReducer)
        # combining groups on the job comparator, not raw bytes
        conf.set_class("mapred.output.key.comparator.class",
                       DeserializingComparator)
        reporter = Reporter()
        copier = ShuffleCopier(conf, SpillChunkSource(spills), n_maps, 0,
                               str(tmp_path), reporter)
        segs = copier.copy_all()
        assert copier.inmem_merges >= 1
        totals: dict = {}
        for s in segs:
            for kb, vb in s:
                k = deserialize(kb)
                totals[k] = totals.get(k, 0) + deserialize(vb)
        assert totals == {k: n_maps * 3 for k in keys}
        assert reporter.counters.value(
            TaskCounter.FRAMEWORK_GROUP,
            TaskCounter.COMBINE_INPUT_RECORDS) > 0
        for s in segs:
            s.close()


class TestDiskBackgroundMerge:
    """The disk-side merger thread (≈ the reference LocalFSMerger):
    accumulated per-segment disk spills fold into sorted runs while the
    copy phase is still fetching."""

    def _disk_segments(self, tmp_path, n, n_recs=120):
        segs, records = [], []
        for m in range(n):
            recs = rand_segments(1, n_recs, seed=100 + m)[0]
            data, index = make_spill(recs)
            p = tmp_path / f"spill-{m}.out"
            p.write_bytes(data)
            off, raw_len, part_len = index["partitions"][0]
            segs.append(DiskSegment(str(p), "none", raw_len,
                                    offset=off + 4, length=part_len - 4))
            records.append(recs)
        return segs, records

    def test_manager_folds_spills_into_runs(self, tmp_path):
        """9 spills at factor 4 → exactly two background merges; the
        ninth stays unmerged (a live segment for the final merge), and
        runs + leftover together hold exactly the input records."""
        conf = conf_for_copier(1.0)
        conf.set("io.sort.factor", 4)
        reporter = Reporter()
        mgr = ShuffleMergeManager(conf, ShuffleRamManager(1 << 20),
                                  str(tmp_path), reporter, None)
        segs, records = self._disk_segments(tmp_path, 9)
        for m, s in enumerate(segs):
            assert mgr.offer_disk(m, s)
        deadline = time.monotonic() + 10
        while mgr.disk_merges < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        runs = mgr.finish()
        assert mgr.disk_merges == 2
        assert mgr.disk_merge_segments == 8
        assert len(runs) == 2
        leftovers = [s for s in segs if id(s) not in mgr.merged_ids]
        assert len(leftovers) == 1
        for run in runs:
            keys = [k for k, _ in run]
            assert keys == sorted(keys)
        got = sorted(kv for src in (*runs, *leftovers) for kv in src)
        assert got == sorted(kv for recs in records for kv in recs)
        assert reporter.counters.value(
            TaskCounter.FRAMEWORK_GROUP,
            TaskCounter.SHUFFLE_DISK_MERGES) == 2
        assert reporter.counters.value(
            TaskCounter.FRAMEWORK_GROUP,
            TaskCounter.SHUFFLE_DISK_MERGE_SEGMENTS) == 8
        for s in (*runs, *leftovers):
            s.close()

    def test_copier_disk_merges_under_slow_wire(self, tmp_path):
        """Copier-level wiring: with a tiny budget (most segments fall
        to disk) and a slow wire, disk merges run mid-copy and the
        merged stream still holds every record."""

        class SlowSource(SpillChunkSource):
            def __call__(self, map_index, partition, offset):
                time.sleep(0.008)
                return super().__call__(map_index, partition, offset)

        n_maps, n_recs = 24, 200
        spills = [make_spill(rand_segments(1, n_recs, seed=m)[0])
                  for m in range(n_maps)]
        seg_raw = spills[0][1]["partitions"][0][1]
        # budget ~2 segments: nearly everything spills to disk
        ram_mb = seg_raw * 2.2 / (0.70 * 1024 * 1024)
        conf = conf_for_copier(ram_mb)
        conf.set("io.sort.factor", 3)
        reporter = Reporter()
        copier = ShuffleCopier(conf, SlowSource(spills), n_maps, 0,
                               str(tmp_path), reporter)
        segs = copier.copy_all()
        assert copier.disk_merges >= 1
        assert reporter.counters.value(
            TaskCounter.FRAMEWORK_GROUP,
            TaskCounter.SHUFFLE_DISK_MERGES) == copier.disk_merges
        bm = merge_engine.BoundedMerge(segs, None, 10,
                                       run_dir=str(tmp_path))
        got = list(bm)
        assert len(got) == n_maps * n_recs
        keys = [k for k, _ in got]
        assert keys == sorted(keys)
        expect = sorted(
            kv for data, idx in spills
            for kv in TestBackgroundMerge._read_spill(data, idx))
        assert sorted(got) == expect
        bm.close()
        for s in segs:
            s.close()
        assert copier.ram.used == 0


# ------------------------------------------------------- mid-batch spills


class TestCollectRawBatchSpill:
    def test_spills_at_threshold_crossings_mid_batch(self, tmp_path):
        from tpumr.mapred.map_task import MapOutputBuffer
        conf = JobConf()
        conf.set("io.sort.mb", 1)
        conf.set("io.sort.spill.percent", 0.01)   # ~10 KB spill threshold
        reporter = Reporter()
        buf = MapOutputBuffer(conf, 1, str(tmp_path), reporter)
        n = 2000                               # ~50 KB >> threshold
        kbs = [serialize(f"k{i:05d}") for i in range(n)]
        vbs = [serialize(i) for i in range(n)]
        buf.collect_raw_batch([0] * n, kbs, vbs)
        # spilled MID-batch, repeatedly — never held the whole batch
        assert len(buf._spills) >= 3
        assert buf._bytes < buf._threshold
        path, index = buf.flush()
        with open(path, "rb") as f:
            got = list(ifile.read_partition(f, index, 0))
        assert len(got) == n
        assert [kb for kb, _ in got] == sorted(kbs,
                                               key=lambda b: deserialize(b))


# ------------------------------------------------------------ e2e cluster


class TestEndToEnd:
    def test_tiny_budget_job_output_identical_and_disk_drops(self):
        """Mini-cluster wordcount with a RAM budget forcing the seed
        path to spill: output bytes identical with the engine on vs
        off, REDUCE_SHUFFLE_SEGMENTS_DISK strictly lower, and at least
        one background merge recorded."""
        from tpumr.fs import FileSystem, get_filesystem
        from tpumr.mapred.job_client import JobClient
        from tpumr.mapred.mini_cluster import MiniMRCluster

        def run(enabled):
            base = JobConf()
            base.set("tpumr.shuffle.ram.mb", 0.35)
            base.set("tpumr.shuffle.merge.enabled", enabled)
            with MiniMRCluster(num_trackers=2, conf=base) as c:
                fs = get_filesystem("mem:///")
                fs.write_bytes("/me/in.txt",
                               b"".join(b"w%03d x\n" % (i % 97)
                                        for i in range(30000)))
                conf = c.create_job_conf()
                conf.set_input_paths("mem:///me/in.txt")
                conf.set_output_path(f"mem:///me/out-{enabled}")
                conf.set("mapred.mapper.class",
                         "tpumr.mapred.lib.TokenCountMapper")
                conf.set("mapred.reducer.class",
                         "tpumr.examples.basic.LongSumReducer")
                conf.set_num_reduce_tasks(2)
                conf.set("mapred.map.tasks", 8)
                conf.set("mapred.min.split.size", 1)
                result = JobClient(conf).run_job(conf)
                assert result.successful
                out = b"".join(
                    fs.read_bytes(st.path)
                    for st in sorted(fs.list_status(f"/me/out-{enabled}"),
                                     key=lambda s: str(s.path))
                    if "part-" in str(st.path))
            FileSystem.clear_cache()
            return out, result.counters

        out_on, counters_on = run(True)
        out_off, counters_off = run(False)
        assert out_on == out_off            # byte-identical job output
        disk_on = counters_on.value(
            TaskCounter.FRAMEWORK_GROUP,
            TaskCounter.REDUCE_SHUFFLE_SEGMENTS_DISK)
        disk_off = counters_off.value(
            TaskCounter.FRAMEWORK_GROUP,
            TaskCounter.REDUCE_SHUFFLE_SEGMENTS_DISK)
        assert counters_on.value(
            TaskCounter.FRAMEWORK_GROUP,
            TaskCounter.SHUFFLE_INMEM_MERGES) >= 1
        assert disk_on < disk_off
