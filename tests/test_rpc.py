"""RPC layer tests ≈ reference ipc tests (src/test/org/apache/hadoop/ipc/:
TestRPC, TestIPC): roundtrips, typed payloads, remote errors, version
handshake, reconnect."""

import threading

import numpy as np
import pytest

from tpumr.ipc.rpc import RpcClient, RpcError, RpcServer, get_proxy


class EchoService:
    def get_protocol_version(self):
        return 7

    def echo(self, x):
        return x

    def add(self, a, b):
        return a + b

    def boom(self):
        raise ValueError("deliberate")

    def _private(self):  # must not be callable remotely
        return "secret"


@pytest.fixture()
def server():
    s = RpcServer(EchoService()).start()
    yield s
    s.stop()


def test_roundtrip_typed_payloads(server):
    cli = RpcClient(*server.address)
    assert cli.call("add", 2, 3) == 5
    assert cli.call("echo", "text é") == "text é"
    assert cli.call("echo", b"\x00raw") == b"\x00raw"
    payload = {"nested": [1, {"k": b"v"}], "arr": np.arange(6).reshape(2, 3)}
    out = cli.call("echo", payload)
    np.testing.assert_array_equal(out["arr"], payload["arr"])
    assert out["nested"] == [1, {"k": b"v"}]
    cli.close()


def test_remote_error_surfaces(server):
    cli = RpcClient(*server.address)
    with pytest.raises(RpcError, match="ValueError: deliberate"):
        cli.call("boom")
    # connection still usable after an error
    assert cli.call("add", 1, 1) == 2
    cli.close()


def test_unknown_and_private_methods_rejected(server):
    cli = RpcClient(*server.address)
    with pytest.raises(RpcError, match="no such method"):
        cli.call("nope")
    with pytest.raises(RpcError, match="no such method"):
        cli.call("_private")
    cli.close()


def test_version_handshake(server):
    proxy = get_proxy(*server.address, protocol_version=7)
    assert proxy.add(4, 5) == 9
    with pytest.raises(RpcError, match="version mismatch"):
        get_proxy(*server.address, protocol_version=29)


def test_concurrent_clients(server):
    results = []

    def worker(i):
        cli = RpcClient(*server.address)
        for j in range(20):
            results.append(cli.call("add", i, j))
        cli.close()

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 160


# ------------------------------------------------- pipelined client calls


class SlowEchoService:
    def get_protocol_version(self):
        return 7

    def echo(self, x):
        return x

    def slow_echo(self, x):
        import time
        time.sleep(0.01)
        return x


def test_call_begin_finish_fifo_reactor():
    from tpumr.ipc.rpc import RpcServer
    s = RpcServer(SlowEchoService(), reactor=True,
                  fast_methods={"get_protocol_version"}).start()
    try:
        cli = RpcClient(*s.address)
        for i in range(6):
            cli.call_begin("slow_echo", i)
        assert cli.outstanding == 6
        # responses collect strictly FIFO — the reactor serves one
        # connection's frames in request order
        assert [cli.call_finish() for _ in range(6)] == list(range(6))
        assert cli.outstanding == 0
        # frames queued behind a busy pooled response stay ordered,
        # fast methods included
        assert s._reactor.pipeline_depth_peak > 1
        cli.close()
    finally:
        s.stop()


def test_call_finish_surfaces_remote_error_in_order():
    from tpumr.ipc.rpc import RpcServer
    s = RpcServer(EchoService(), reactor=True).start()
    try:
        cli = RpcClient(*s.address)
        cli.call_begin("echo", "a")
        cli.call_begin("boom")
        cli.call_begin("echo", "b")
        assert cli.call_finish() == "a"
        with pytest.raises(RpcError, match="deliberate"):
            cli.call_finish()
        assert cli.call_finish() == "b"
        cli.close()
    finally:
        s.stop()


def test_client_pool_reuses_and_retires():
    from tpumr.ipc.rpc import RpcClientPool, RpcServer
    s = RpcServer(EchoService(), reactor=True).start()
    addr = "%s:%d" % s.address
    pool = RpcClientPool(lambda h, p: RpcClient(h, p), conns_per_target=2)
    try:
        a = pool.acquire(addr)
        assert a.call("add", 1, 2) == 3
        pool.release(addr, a)
        b = pool.acquire(addr)
        assert b is a                 # idle connection reused
        assert pool.connects == 1
        # a lease returned with uncollected responses is NEVER reused:
        # the next caller would read the stale frames
        b.call_begin("echo", "x")
        assert b.outstanding == 1
        pool.release(addr, b)
        c = pool.acquire(addr)
        assert c is not b
        assert pool.connects == 2
        pool.release(addr, c)
    finally:
        pool.close()
        s.stop()


def test_client_pool_caps_connections_per_target():
    from tpumr.ipc.rpc import RpcClientPool, RpcServer
    s = RpcServer(EchoService(), reactor=True).start()
    addr = "%s:%d" % s.address
    pool = RpcClientPool(lambda h, p: RpcClient(h, p), conns_per_target=1)
    try:
        a = pool.acquire(addr)
        with pytest.raises(TimeoutError):
            pool.acquire(addr, timeout_s=0.05)
        pool.release(addr, a)
        b = pool.acquire(addr)    # freed slot satisfies the waiter
        assert b is a
        pool.release(addr, b)
    finally:
        pool.close()
        s.stop()
