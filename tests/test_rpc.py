"""RPC layer tests ≈ reference ipc tests (src/test/org/apache/hadoop/ipc/:
TestRPC, TestIPC): roundtrips, typed payloads, remote errors, version
handshake, reconnect."""

import threading

import numpy as np
import pytest

from tpumr.ipc.rpc import RpcClient, RpcError, RpcServer, get_proxy


class EchoService:
    def get_protocol_version(self):
        return 7

    def echo(self, x):
        return x

    def add(self, a, b):
        return a + b

    def boom(self):
        raise ValueError("deliberate")

    def _private(self):  # must not be callable remotely
        return "secret"


@pytest.fixture()
def server():
    s = RpcServer(EchoService()).start()
    yield s
    s.stop()


def test_roundtrip_typed_payloads(server):
    cli = RpcClient(*server.address)
    assert cli.call("add", 2, 3) == 5
    assert cli.call("echo", "text é") == "text é"
    assert cli.call("echo", b"\x00raw") == b"\x00raw"
    payload = {"nested": [1, {"k": b"v"}], "arr": np.arange(6).reshape(2, 3)}
    out = cli.call("echo", payload)
    np.testing.assert_array_equal(out["arr"], payload["arr"])
    assert out["nested"] == [1, {"k": b"v"}]
    cli.close()


def test_remote_error_surfaces(server):
    cli = RpcClient(*server.address)
    with pytest.raises(RpcError, match="ValueError: deliberate"):
        cli.call("boom")
    # connection still usable after an error
    assert cli.call("add", 1, 1) == 2
    cli.close()


def test_unknown_and_private_methods_rejected(server):
    cli = RpcClient(*server.address)
    with pytest.raises(RpcError, match="no such method"):
        cli.call("nope")
    with pytest.raises(RpcError, match="no such method"):
        cli.call("_private")
    cli.close()


def test_version_handshake(server):
    proxy = get_proxy(*server.address, protocol_version=7)
    assert proxy.add(4, 5) == 9
    with pytest.raises(RpcError, match="version mismatch"):
        get_proxy(*server.address, protocol_version=29)


def test_concurrent_clients(server):
    results = []

    def worker(i):
        cli = RpcClient(*server.address)
        for j in range(20):
            results.append(cli.call("add", i, j))
        cli.close()

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 160
