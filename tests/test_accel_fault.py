"""Accelerator fault tolerance — failure-classified retries, TPU→CPU
demotion, job-level TPU quarantine, per-device tracker quarantine, and
hung-task reaping (≈ mapred.task.timeout + TaskTracker's
markUnresponsiveTasks; demotion/quarantine are new capabilities over the
reference, which re-lands a deterministically-crashing kernel on the
same backend until the job dies).

The two mini-cluster chaos e2es at the bottom are the acceptance runs:
persistent injected TPU execute failures must complete byte-identically
to a CPU-only run via the demotion path, and an injected hung map must
be reaped within ``mapred.task.timeout`` with the job finishing
byte-correct. ``TPUMR_FI_SEED`` pins the fault-injection RNG (the CI
chaos-smoke job sets it)."""

import os
import time
from collections import Counter

import pytest

from tpumr.core.counters import JobCounter
from tpumr.mapred.ids import JobID, TaskAttemptID
from tpumr.mapred.job_in_progress import JobInProgress, JobState
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.node_health import TpuDeviceHealth
from tpumr.mapred.task import (FailureClass, TaskState, TaskStatus,
                               classify_accelerator_exception,
                               classify_exception, tag_failure)
from tpumr.utils import fi

FI_SEED = os.environ.get("TPUMR_FI_SEED", "20260804")


def _conf(**kv):
    conf = JobConf()
    for k, v in kv.items():
        conf.set(k, v)
    return conf


# ------------------------------------------------------- classification


class TestFailureClassification:
    def test_site_tag_wins(self):
        e = tag_failure(RuntimeError("boom"), FailureClass.DEVICE)
        assert classify_exception(e) == "device"
        # first stamp wins — a later tag cannot reclassify
        tag_failure(e, FailureClass.USER)
        assert classify_exception(e) == "device"

    def test_memory_errors_are_oom(self):
        assert classify_exception(MemoryError()) == "oom"
        assert classify_exception(
            RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying "
                         "to allocate")) == "oom"

    def test_default_is_user(self):
        assert classify_exception(TypeError("unhashable")) == "user"

    def test_cold_compile_text_classes_compile(self):
        e = RuntimeError("Mosaic lowering failed: unsupported op")
        assert classify_accelerator_exception(
            e, compile_cold=True) == "compile"
        # the same error on a WARM dispatch is not a compile failure
        assert classify_accelerator_exception(
            e, compile_cold=False) == "user"

    def test_xla_errors_are_device(self):
        e = RuntimeError("INTERNAL: XLA stream executor failure")
        assert classify_accelerator_exception(e) == "device"

    def test_injected_fault_carries_class(self):
        fi.reset()
        conf = _conf(**{"tpumr.fi.classed.point.probability": 1.0})
        with pytest.raises(fi.InjectedFault) as ei:
            fi.maybe_fail("classed.point", conf,
                          failure_class=FailureClass.DEVICE)
        assert classify_exception(ei.value) == "device"
        fi.reset()


class TestFiresSeam:
    def setup_method(self):
        fi.reset()

    def teardown_method(self):
        fi.reset()

    def test_fires_honors_probability_and_limit(self):
        conf = _conf(**{"tpumr.fi.behave.probability": 1.0,
                        "tpumr.fi.behave.max.failures": 2})
        assert [fi.fires("behave", conf) for _ in range(4)] == \
            [True, True, False, False]
        assert fi.fired("behave") == 2
        assert fi.fires("behave", None) is False
        assert fi.fires("unconfigured", conf) is False

    def test_fires_and_maybe_fail_share_determinism(self):
        a = _conf(**{"tpumr.fi.det.probability": 0.5,
                     "tpumr.fi.seed": FI_SEED})
        seq = [fi.fires("det", a) for _ in range(64)]
        fi.reset()
        seq2 = []
        for _ in range(64):
            try:
                fi.maybe_fail("det", a)
                seq2.append(False)
            except fi.InjectedFault:
                seq2.append(True)
        assert seq == seq2 and 0 < sum(seq) < 64


# ------------------------------------------ JIP demotion / quarantine


def _job(n_maps=2, n_reduces=1, **conf):
    base = {"mapred.reduce.tasks": n_reduces,
            "mapred.speculative.execution": False,
            "mapred.reduce.slowstart.completed.maps": 0.0,
            "tpumr.map.kernel": "sleep"}
    base.update(conf)
    return JobInProgress(JobID("af", 1),
                         splits=[{"locations": []} for _ in range(n_maps)],
                         conf_dict=base)


def _fail_attempt(job, task, failure_class="", on_tpu=True, runtime=1.0):
    now = time.time()
    job.update_task_status(TaskStatus(
        attempt_id=task.attempt_id, is_map=task.is_map, run_on_tpu=on_tpu,
        tpu_device_id=task.tpu_device_id, state=TaskState.FAILED,
        failure_class=failure_class, start_time=now - runtime,
        finish_time=now), "t:0")


def _finish(job, task, runtime=1.0, on_tpu=False):
    now = time.time()
    job.update_task_status(TaskStatus(
        attempt_id=task.attempt_id, is_map=task.is_map, run_on_tpu=on_tpu,
        state=TaskState.SUCCEEDED, start_time=now - runtime,
        finish_time=now), "t:0")


class TestTipDemotion:
    def test_device_failure_pins_tip_cpu_only(self):
        job = _job(n_maps=1)
        t = job.obtain_new_map_task("h", run_on_tpu=True, tpu_device_id=0)
        _fail_attempt(job, t, FailureClass.DEVICE)
        # the re-queued TIP is invisible to the TPU pass, visible to CPU
        assert job.obtain_new_map_task("h", run_on_tpu=True,
                                       tpu_device_id=0) is None
        cpu = job.obtain_new_map_task("h", run_on_tpu=False)
        assert cpu is not None and not cpu.run_on_tpu
        assert job.counters.value(JobCounter.GROUP,
                                  JobCounter.TPU_DEMOTIONS) == 1
        events = job.drain_accel_events()
        assert [e["kind"] for e in events] == ["tip_demoted"]
        assert events[0]["failure_class"] == "device"
        assert job.drain_accel_events() == []   # drained exactly once
        assert job.status_dict()["tpu_demoted_tips"] == 1

    def test_compile_failures_demote_too(self):
        job = _job(n_maps=1)
        t = job.obtain_new_map_task("h", run_on_tpu=True, tpu_device_id=0)
        _fail_attempt(job, t, FailureClass.COMPILE)
        assert job.obtain_new_map_task("h", run_on_tpu=True) is None

    def test_user_and_unclassified_failures_do_not_demote(self):
        for fc in (FailureClass.USER, FailureClass.OOM,
                   FailureClass.TIMEOUT, ""):
            job = _job(n_maps=1)
            t = job.obtain_new_map_task("h", run_on_tpu=True,
                                        tpu_device_id=0)
            _fail_attempt(job, t, fc)
            again = job.obtain_new_map_task("h", run_on_tpu=True,
                                            tpu_device_id=0)
            assert again is not None, f"class {fc!r} must not demote"
            assert job.counters.value(JobCounter.GROUP,
                                      JobCounter.TPU_DEMOTIONS) == 0

    def test_cpu_failures_never_demote(self):
        job = _job(n_maps=1)
        t = job.obtain_new_map_task("h", run_on_tpu=False)
        _fail_attempt(job, t, FailureClass.DEVICE, on_tpu=False)
        assert job.obtain_new_map_task("h", run_on_tpu=True,
                                       tpu_device_id=0) is not None

    def test_retries_knob_allows_more_tpu_attempts(self):
        job = _job(n_maps=1, **{"tpumr.tpu.attempt.retries": 2})
        t = job.obtain_new_map_task("h", run_on_tpu=True, tpu_device_id=0)
        _fail_attempt(job, t, FailureClass.DEVICE)
        t2 = job.obtain_new_map_task("h", run_on_tpu=True, tpu_device_id=0)
        assert t2 is not None          # one more TPU try allowed
        _fail_attempt(job, t2, FailureClass.DEVICE)
        assert job.obtain_new_map_task("h", run_on_tpu=True,
                                       tpu_device_id=0) is None
        assert job.maps[0].tpu_failures == 2

    def test_demoted_tip_keeps_attempt_budget_for_cpu(self):
        """Demotion must not eat into mapred.map.max.attempts beyond the
        failures that actually happened."""
        job = _job(n_maps=1, **{"mapred.map.max.attempts": 3})
        t = job.obtain_new_map_task("h", run_on_tpu=True, tpu_device_id=0)
        _fail_attempt(job, t, FailureClass.DEVICE)
        assert job.state == JobState.RUNNING
        assert job.maps[0].failures == 1
        cpu = job.obtain_new_map_task("h", run_on_tpu=False)
        _finish(job, cpu)
        assert job.maps[0].state == "succeeded"


class TestJobTpuQuarantine:
    def _quarantine(self, job, n_tips=3):
        for _ in range(n_tips):
            t = job.obtain_new_map_task("h", run_on_tpu=True,
                                        tpu_device_id=0)
            assert t is not None
            _fail_attempt(job, t, FailureClass.DEVICE)

    def test_distinct_tips_disable_the_tpu_pass(self):
        job = _job(n_maps=4, **{"tpumr.tpu.job.quarantine.tips": 3})
        self._quarantine(job)
        assert job.tpu_disabled
        assert not job.tpu_eligible()
        assert job.obtain_new_map_task("h", run_on_tpu=True,
                                       tpu_device_id=0) is None
        # the 4th (never-TPU-failed) map still runs on CPU
        assert job.obtain_new_map_task("h", run_on_tpu=False) is not None
        kinds = [e["kind"] for e in job.drain_accel_events()]
        assert kinds.count("job_tpu_quarantined") == 1
        assert job.status_dict()["tpu_disabled"] is True

    def test_one_tip_failing_repeatedly_is_not_a_job_quarantine(self):
        job = _job(n_maps=4, **{"tpumr.tpu.job.quarantine.tips": 3,
                                "tpumr.tpu.attempt.retries": 10,
                                "mapred.map.max.attempts": 20})
        for _ in range(5):
            t = job.obtain_new_map_task("h", run_on_tpu=True,
                                        tpu_device_id=0)
            _fail_attempt(job, t, FailureClass.DEVICE)
        assert not job.tpu_disabled   # one tip, many failures: not 3 TIPs

    def test_profile_sums_unwound_and_factor_reset(self):
        job = _job(n_maps=5, **{"tpumr.tpu.job.quarantine.tips": 3})
        # profile data on both backends first: TPU looks 4x faster
        t = job.obtain_new_map_task("h", run_on_tpu=True, tpu_device_id=0)
        _finish(job, t, runtime=1.0, on_tpu=True)
        c = job.obtain_new_map_task("h", run_on_tpu=False)
        _finish(job, c, runtime=4.0, on_tpu=False)
        assert job.acceleration_factor() == pytest.approx(4.0)
        self._quarantine(job)
        assert job.tpu_disabled
        assert job.finished_tpu_maps == 0
        assert job._tpu_time_sum == pytest.approx(0.0)
        assert job.acceleration_factor() == 1.0
        # an in-flight TPU completion trickling in post-quarantine must
        # not resurrect the poisoned factor (still counts as a finished
        # map — the work is real)
        finished = job.finished_maps
        straggler = job.maps[4]
        aid = TaskAttemptID(straggler.task_id, 7)
        now = time.time()
        job.update_task_status(TaskStatus(
            attempt_id=aid, is_map=True, run_on_tpu=True,
            state=TaskState.SUCCEEDED, start_time=now - 0.5,
            finish_time=now), "t:0")
        assert job.finished_maps == finished + 1
        assert job.finished_tpu_maps == 0
        assert job.acceleration_factor() == 1.0
        # ...and it must not be misattributed to the CPU profile either
        assert job.finished_cpu_maps == 1
        assert job._cpu_time_sum == pytest.approx(4.0)


class TestSchedulerQuarantineInteraction:
    def test_optional_scheduling_deadlock_broken_by_quarantine(self):
        """The regression this PR exists for: a quarantined job under
        optional scheduling used to keep a zero CPU budget while the TPU
        pass skipped it — pending maps no pass could ever assign."""
        from test_scheduler import (finish_map, make_job, make_scheduler,
                                    tracker_status)
        job = make_job(n_maps=8, optional=True)
        sched = make_scheduler([job])
        # profile both backends so optional scheduling's starvation rule
        # is live (TPU 10x faster; pending < accel * capacity)
        t = job.obtain_new_map_task("h", run_on_tpu=True, tpu_device_id=0)
        finish_map(job, t, runtime=0.1, on_tpu=True)
        c = job.obtain_new_map_task("h", run_on_tpu=False)
        finish_map(job, c, runtime=1.0, on_tpu=False)
        # starvation active: the CPU pass assigns nothing (only the TPU
        # pass places work)
        before = sched.assign_tasks(tracker_status(cpu=3, tpu=1,
                                                   reduce=0))
        assert before and all(x.run_on_tpu for x in before)
        job.tpu_disabled = True
        tasks = sched.assign_tasks(tracker_status(cpu=3, tpu=1,
                                                  reduce=0))
        assert tasks, "quarantined job must fall back to the CPU pass"
        assert all(not x.run_on_tpu for x in tasks)

    def test_tpu_pass_skips_quarantined_job_for_next_in_queue(self):
        from test_scheduler import make_job, make_scheduler, tracker_status
        quarantined = make_job(n_maps=4, job_num=1)
        quarantined.tpu_disabled = True
        healthy = make_job(n_maps=4, job_num=2)
        sched = make_scheduler([quarantined, healthy])
        tasks = sched.assign_tasks(tracker_status(cpu=0, tpu=1, reduce=0))
        assert len(tasks) == 1 and tasks[0].run_on_tpu
        assert tasks[0].attempt_id.task.job == healthy.job_id


# ------------------------------------------------------- device health


class TestTpuDeviceHealth:
    def test_consecutive_threshold_and_streak_reset(self):
        dh = TpuDeviceHealth(2, threshold=3, probe=lambda d: None,
                             probe_interval_s=3600)
        try:
            assert not dh.record_failure(0)
            assert not dh.record_failure(0)
            dh.record_success(0)            # streak broken
            assert not dh.record_failure(0)
            assert not dh.record_failure(0)
            assert dh.record_failure(0)     # third consecutive: bad
            assert dh.quarantined() == [0]
            assert dh.is_quarantined(0) and not dh.is_quarantined(1)
            # further failures on a quarantined device are not new events
            assert not dh.record_failure(0)
            assert dh.quarantine_events == 1
        finally:
            dh.stop()

    def test_probe_restores_and_backs_off_capped(self):
        sick = [True]
        probes = []

        def probe(d):
            probes.append(d)
            if sick[0]:
                raise RuntimeError("still dead")

        dh = TpuDeviceHealth(1, threshold=1, probe=probe,
                             probe_interval_s=1.0, probe_max_interval_s=4.0)
        try:
            assert dh.record_failure(0)
            now = time.monotonic()
            # deterministic probe driving: each failed probe doubles the
            # backoff up to the cap (1 → 2 → 4 → 4)
            deadlines = []
            for _ in range(4):
                at, backoff = dh._quarantined[0]
                deadlines.append(backoff)
                assert dh.probe_once(now=at) == []
            assert deadlines == [1.0, 2.0, 4.0, 4.0]
            assert dh.quarantined() == [0]
            sick[0] = False               # the injected fault clears
            at, _ = dh._quarantined[0]
            assert dh.probe_once(now=at) == [0]
            assert dh.quarantined() == []
            assert dh.restore_events == 1
            assert len(probes) == 5
            # requarantine works after a restore
            assert dh.record_failure(0)
        finally:
            dh.stop()

    def test_zero_threshold_disables(self):
        dh = TpuDeviceHealth(1, threshold=0, probe=lambda d: None)
        assert not dh.record_failure(0)
        assert dh.quarantined() == []
        dh.stop()


class TestTrackerDeviceQuarantine:
    def test_quarantine_shrinks_heartbeat_slots_and_probe_restores(self):
        """Acceptance: quarantine observably shrinks the tracker's
        advertised TPU slots on heartbeat; the probe restores them once
        the fault clears."""
        from tpumr.mapred.mini_cluster import MiniMRCluster
        base = JobConf()
        base.set("tpumr.tpu.device.quarantine.failures", 2)
        with MiniMRCluster(num_trackers=1, conf=base, cpu_slots=1,
                           tpu_slots=2, tpu_devices_per_tracker=2) as c:
            tracker = c.trackers[0]
            dh = tracker.device_health
            assert dh is not None and dh.threshold == 2
            sick = [True]

            def probe(d):
                if sick[0]:
                    raise RuntimeError("injected device fault")

            dh.probe = probe
            dh.record_failure(1)
            assert dh.record_failure(1)          # 2 consecutive: bad
            st = tracker._status_dict()
            assert st["max_tpu_map_slots"] == 1  # 2 - 1 quarantined
            assert st["quarantined_tpu_devices"] == [1]
            assert st["available_tpu_devices"][1] is False

            # the master sees the shrunken pool on the next heartbeat
            deadline = time.time() + 5
            while time.time() < deadline:
                with c.master.lock:
                    infos = list(c.master.trackers.values())
                if infos and infos[0].status.get(
                        "quarantined_tpu_devices") == [1]:
                    break
                time.sleep(0.05)
            else:
                pytest.fail("master never saw the quarantined device")
            assert c.master.total_slots()["tpu"] == 1
            snap = c.master.metrics.snapshot()["jobtracker"]
            assert snap["tpu_devices_quarantined"] == 1

            # fault clears → the probe re-admits the device
            sick[0] = False
            at, _ = dh._quarantined[1]
            assert dh.probe_once(now=at) == [1]
            st = tracker._status_dict()
            assert st["max_tpu_map_slots"] == 2
            assert st["quarantined_tpu_devices"] == []
            assert st["available_tpu_devices"][1] is True


# ------------------------------------------- health-report visibility


class TestHealthReportSurfaced:
    def test_unhealthy_reason_in_active_trackers_and_page(self):
        """Satellite: the NodeHealthChecker ERROR reason reaches the
        cluster-wide surfaces (`-list-active-trackers` output and the
        JT /trackers page), not just the node itself."""
        from tpumr.mapred.jobtracker import JobMaster
        jm = JobMaster(_conf())
        try:
            def beat(name, healthy, report=""):
                jm.heartbeat({
                    "tracker_name": name, "host": "127.0.0.1",
                    "shuffle_port": 0, "max_cpu_map_slots": 1,
                    "max_tpu_map_slots": 0, "max_reduce_slots": 1,
                    "count_cpu_map_tasks": 0, "count_tpu_map_tasks": 0,
                    "count_reduce_tasks": 0, "task_statuses": [],
                    "healthy": healthy, "health_report": report,
                }, True, False, 0)

            beat("tr_ok", True)
            beat("tr_sick", False, "ERROR disk full on /scratch")
            active = jm.get_active_trackers()
            assert "tr_ok" in active
            sick = [a for a in active if a.startswith("tr_sick")]
            assert sick and "ERROR disk full on /scratch" in sick[0]
        finally:
            jm.stop()


# ------------------------------------------------- recovery satellites


class TestRecoveryFailurePaths:
    def _master(self, tmp_path):
        from tpumr.mapred.jobtracker import JobMaster
        conf = JobConf()
        conf.set("tpumr.history.dir", str(tmp_path))
        conf.set("mapred.jobtracker.restart.recover", True)
        return JobMaster(conf)

    def _write_submitted(self, tmp_path, job_id, **extra):
        import json
        ev = {"event": "JOB_SUBMITTED", "job_id": job_id,
              "conf": {"mapred.job.name": "wreck",
                       "mapred.reduce.tasks": 0},
              "conf_dropped": [], "splits": [{"locations": []}]}
        ev.update(extra)
        with open(os.path.join(str(tmp_path), f"{job_id}.jsonl"),
                  "a") as f:
            f.write(json.dumps(ev) + "\n")

    def _events(self, tmp_path, job_id):
        from tpumr.mapred.history import JobHistory
        return JobHistory.read(os.path.join(str(tmp_path),
                                            f"{job_id}.jsonl"))

    def test_conf_dropped_skips_and_flags(self, tmp_path):
        self._write_submitted(tmp_path, "job_x_0001",
                              conf_dropped=["mapred.mapper.class"])
        jm = self._master(tmp_path).start()
        try:
            assert jm.jobs == {}   # NOT resubmitted broken
            snap = jm.metrics.snapshot()["jobtracker"]
            assert snap["jobs_recovery_failed"] == 1
            assert snap.get("jobs_recovered", 0) == 0
        finally:
            jm.stop()
        evs = self._events(tmp_path, "job_x_0001")
        failed = [e for e in evs if e["event"] == "JOB_RECOVERY_FAILED"]
        assert len(failed) == 1
        assert "mapred.mapper.class" in failed[0]["error"]
        # the failure marker is terminal: a second restart doesn't retry
        jm2 = self._master(tmp_path).start()
        try:
            assert jm2.metrics.snapshot()["jobtracker"].get(
                "jobs_recovery_failed", 0) == 0
        finally:
            jm2.stop()

    def test_submit_raise_flags_and_continues(self, tmp_path):
        # splits that blow up JobInProgress construction inside submit_job
        self._write_submitted(tmp_path, "job_x_0001", splits=17)
        self._write_submitted(tmp_path, "job_x_0002")   # healthy sibling
        jm = self._master(tmp_path).start()
        try:
            snap = jm.metrics.snapshot()["jobtracker"]
            assert snap["jobs_recovery_failed"] == 1
            assert snap["jobs_recovered"] == 1   # the sibling made it
            assert len(jm.jobs) == 1
        finally:
            jm.stop()
        evs = self._events(tmp_path, "job_x_0001")
        assert [e["event"] for e in evs
                if e["event"].startswith("JOB_RECOVERY")] \
            == ["JOB_RECOVERY_FAILED"]


# ------------------------------------------------------------ e2e chaos


def _register_faultcount_kernel():
    """A wordcount-style kernel whose TPU and CPU batch paths emit
    identical records — the byte-identity contract the demotion e2e
    asserts. Registered in-process (the mini-cluster shares this
    interpreter)."""
    from tpumr.ops.registry import KernelMapper, register_kernel

    def _count(batch):
        counts = Counter()
        for _k, v in batch:
            counts.update(bytes(v).split())
        return sorted(counts.items())

    class FaultCountKernel(KernelMapper):
        name = "faultcount"

        def map_batch(self, batch, conf, task):
            return _count(batch)

        map_batch_cpu = staticmethod(lambda batch, conf, task:
                                     _count(batch))

    return register_kernel(FaultCountKernel())


def _run_wordcount_job(cluster, fs, in_path, out_path, kernel=None,
                       **conf_kv):
    from tpumr.mapred.job_client import JobClient
    conf = cluster.create_job_conf()
    conf.set_input_paths(in_path)
    conf.set_output_path(out_path)
    conf.set("mapred.mapper.class", "tpumr.mapred.lib.TokenCountMapper")
    conf.set("mapred.reducer.class", "tpumr.examples.basic.LongSumReducer")
    conf.set("mapred.map.tasks", 4)
    conf.set_num_reduce_tasks(1)
    if kernel:
        conf.set_map_kernel(kernel)
    for k, v in conf_kv.items():
        conf.set(k, v)
    return JobClient(conf).run_job(conf)


def _output_bytes(fs, out_dir):
    return b"".join(fs.read_bytes(st.path)
                    for st in sorted(fs.list_status(out_dir),
                                     key=lambda s: str(s.path))
                    if "part-" in str(st.path))


def _write_input(fs, path, n=2000):
    fs.write_bytes(path, b"".join(b"w%02d x\n" % (i % 23)
                                  for i in range(n)))


class TestEndToEndDemotionChaos:
    def test_persistent_tpu_faults_complete_via_cpu_demotion(self, tmp_path):
        """Acceptance: with tpumr.fi injecting PERSISTENT TPU execute
        failures, the job completes byte-identically to a CPU-only run,
        TPU_DEMOTIONS > 0, and the job never fails. Also exports the
        merged job trace for the CI chaos-smoke artifact."""
        fi.reset()
        from tpumr.fs import FileSystem, get_filesystem
        from tpumr.mapred.mini_cluster import MiniMRCluster
        _register_faultcount_kernel()
        try:
            fs = get_filesystem("mem:///")
            _write_input(fs, "/af/in.txt")

            # control: CPU-only cluster (no TPU slots at all)
            with MiniMRCluster(num_trackers=2, cpu_slots=2,
                               tpu_slots=0) as c:
                control = _run_wordcount_job(c, fs, "mem:///af/in.txt",
                                             "mem:///af/out-cpu",
                                             kernel="faultcount")
                assert control.successful
                want = _output_bytes(fs, "/af/out-cpu")
            assert want  # the control run must actually produce bytes

            # chaos: every TPU execution fails, persistently, classed
            # device — the demotion path is the only road to completion
            base = JobConf()
            base.set("tpumr.fi.tpu.execute.probability", 1.0)
            base.set("tpumr.fi.seed", FI_SEED)
            base.set("tpumr.trace.enabled", True)
            base.set("tpumr.history.dir", str(tmp_path))
            with MiniMRCluster(num_trackers=2, conf=base, cpu_slots=2,
                               tpu_slots=1) as c:
                result = _run_wordcount_job(
                    c, fs, "mem:///af/in.txt", "mem:///af/out-chaos",
                    kernel="faultcount",
                    **{"tpumr.tpu.job.quarantine.tips": 3})
                assert result.successful, \
                    "persistent TPU faults must demote, not fail the job"
                got = _output_bytes(fs, "/af/out-chaos")
                assert got == want, "demotion path must be byte-identical"

                jip = c.master.jobs[str(result.job_id)]
                assert jip.counters.value(
                    JobCounter.GROUP, JobCounter.TPU_DEMOTIONS) > 0
                assert fi.fired("tpu.execute") > 0
                # every demoted attempt failed classed `device`
                classes = {s.failure_class
                           for tip in jip.maps
                           for s in tip.attempts.values()
                           if s.state == TaskState.FAILED}
                assert classes == {"device"}
                snap = c.master.metrics.snapshot()["jobtracker"]
                assert snap["tpu_demotions"] > 0
                # history carries the decisions
                evs = [e["event"] for e in c.master.history.read(
                    os.path.join(str(tmp_path),
                                 f"{result.job_id}.jsonl"))]
                assert "TIP_TPU_DEMOTED" in evs

                # CI artifact: the merged chaos-run job trace
                from tpumr.core import tracing
                trace = c.master.get_job_trace(str(result.job_id))
                assert trace["spans"], "chaos run must be traced"
                import json
                with open("/tmp/tpumr-chaos-trace.json", "w") as f:
                    json.dump(tracing.to_chrome_trace(trace["spans"]), f)
        finally:
            fi.reset()
            FileSystem.clear_cache()


class TestEndToEndHungTaskReap:
    def test_hung_map_is_reaped_and_job_completes(self):
        """Acceptance: an injected hung map (stops reporting progress
        mid-map) is reaped within mapred.task.timeout with
        failure_class=timeout; the re-run completes the job
        byte-correct."""
        fi.reset()
        from tpumr.fs import FileSystem, get_filesystem
        from tpumr.mapred.mini_cluster import MiniMRCluster
        base = JobConf()
        base.set("mapred.task.timeout", 1500)   # ms, Hadoop-compatible
        base.set("tpumr.fi.task.hang.m0.probability", 1.0)
        base.set("tpumr.fi.task.hang.m0.max.failures", 1)
        base.set("tpumr.fi.seed", FI_SEED)
        try:
            fs = get_filesystem("mem:///")
            _write_input(fs, "/reap/in.txt")
            with MiniMRCluster(num_trackers=2, conf=base, cpu_slots=2,
                               tpu_slots=0) as c:
                t0 = time.monotonic()
                result = _run_wordcount_job(c, fs, "mem:///reap/in.txt",
                                            "mem:///reap/out")
                wall = time.monotonic() - t0
                assert result.successful, "the reaped map must re-run"
                counts = dict(line.split(b"\t") for line in
                              _output_bytes(fs, "/reap/out").splitlines())
                assert counts[b"x"] == b"2000"
                assert fi.fired("task.hang.m0") == 1

                jip = c.master.jobs[str(result.job_id)]
                reaped = [s for tip in jip.maps
                          for s in tip.attempts.values()
                          if s.state == TaskState.FAILED]
                assert len(reaped) == 1
                assert reaped[0].failure_class == "timeout"
                assert "failed to report status" in reaped[0].diagnostics
                # reaped within the timeout (plus reaper granularity +
                # retry wall time — generous bound, but far below the
                # 600s a timeout-less attempt would burn)
                assert wall < 30
                snap = c.master.metrics.snapshot()["jobtracker"]
                assert snap["tasks_reaped_timeout"] == 1
                assert jip.counters.value(
                    JobCounter.GROUP, JobCounter.TASKS_REAPED_TIMEOUT) == 1
                t_snaps = [t.metrics.snapshot()[t.name].get(
                    "tasks_reaped_timeout", 0) for t in c.trackers]
                assert sum(t_snaps) == 1
                # the hung attempt burned one attempt, like Hadoop's
                # "failed to report status ... Killing!"
                assert sum(t.failures for t in jip.maps) == 1
        finally:
            fi.reset()
            FileSystem.clear_cache()

    def test_hung_isolated_child_is_sigkilled_and_reaped(self, tmp_path):
        """Process-isolation variant: the hung child keeps its umbilical
        ping and 1 Hz status push alive (neither counts as progress), is
        reaped at the timeout, and its whole process tree is SIGKILLed
        via _kill_tree; the re-run completes the job. Local files, not
        mem:// — isolated children live in their own process and cannot
        see this process's in-memory filesystem."""
        fi.reset()
        from tpumr.fs import FileSystem
        from tpumr.mapred.mini_cluster import MiniMRCluster
        base = JobConf()
        base.set("mapred.task.timeout", 2000)
        base.set("tpumr.task.isolation", "process")
        # the hang comes from the sleep example's attempt-aware mode,
        # not the fi seam: fi's max.failures ledger is per-process, and
        # each isolated attempt is a FRESH process — the seam would
        # hang every re-run too
        in_path = tmp_path / "in.txt"
        in_path.write_bytes(b"0\n1\n2\n")
        try:
            with MiniMRCluster(num_trackers=1, conf=base, cpu_slots=2,
                               tpu_slots=0) as c:
                from tpumr.examples.sleep import SleepMapper, SleepReducer
                from tpumr.mapred.input_formats import NLineInputFormat
                from tpumr.mapred.job_client import JobClient
                conf = c.create_job_conf()
                conf.set_input_paths(str(in_path))
                conf.set_output_path(str(tmp_path / "out"))
                conf.set_input_format(NLineInputFormat)
                conf.set("mapred.line.input.format.linespermap", 1)
                conf.set_mapper_class(SleepMapper)
                conf.set_reducer_class(SleepReducer)
                conf.set("tpumr.sleep.map.ms", 20)
                # map 1's FIRST attempt hangs (attempt-aware, so the
                # re-run — a fresh child process — runs clean)
                conf.set("tpumr.sleep.hang.map", 1)
                result = JobClient(conf).run_job(conf)
                assert result.successful
                jip = c.master.jobs[str(result.job_id)]
                reaped = [s for tip in jip.maps
                          for s in tip.attempts.values()
                          if s.state == TaskState.FAILED]
                assert len(reaped) == 1
                assert reaped[0].failure_class == "timeout"
                snap = c.master.metrics.snapshot()["jobtracker"]
                assert snap["tasks_reaped_timeout"] == 1
        finally:
            fi.reset()
            FileSystem.clear_cache()

    def test_healthy_tasks_survive_a_tight_timeout(self):
        """Counter-case: a normally-progressing job with the same tight
        timeout is never reaped — progress observation keeps live
        attempts alive."""
        fi.reset()
        from tpumr.fs import FileSystem, get_filesystem
        from tpumr.mapred.mini_cluster import MiniMRCluster
        base = JobConf()
        base.set("mapred.task.timeout", 1500)
        try:
            fs = get_filesystem("mem:///")
            _write_input(fs, "/ok/in.txt")
            with MiniMRCluster(num_trackers=1, conf=base, cpu_slots=2,
                               tpu_slots=0) as c:
                result = _run_wordcount_job(c, fs, "mem:///ok/in.txt",
                                            "mem:///ok/out")
                assert result.successful
                snap = c.master.metrics.snapshot()["jobtracker"]
                assert snap.get("tasks_reaped_timeout", 0) == 0
        finally:
            fi.reset()
            FileSystem.clear_cache()
