"""Device-resident output chaining (tpumr/mapred/device_output.py):
a kernel job writing DenseNpyOutputFormat publishes its device output;
a chained DenseInputFormat job consumes it from HBM — zero storage read,
zero re-upload (extends the HBM input split cache to OUTPUTS)."""

import numpy as np
import pytest

from tpumr.fs import FileSystem
from tpumr.mapred import JobConf, run_job
from tpumr.mapred import device_output
from tpumr.mapred.input_formats import DenseInputFormat
from tpumr.mapred.output_formats import DenseNpyOutputFormat
from tpumr.mapred.tpu_runner import clear_split_caches


@pytest.fixture(autouse=True)
def _fresh():
    clear_split_caches()
    yield
    clear_split_caches()
    FileSystem.clear_cache()


class TestFingerprint:
    def test_head_tail_mirrors_lookup_reads(self, tmp_path):
        data = bytes(range(256)) * 64          # 16 KB
        p = tmp_path / "f.bin"
        p.write_bytes(data)
        head, tail, size = device_output.head_tail(data)
        with open(p, "rb") as f:
            rhead = f.read(4096)
            f.seek(max(4096, size - 4096))
            rtail = f.read(4096)
        assert (rhead, rtail, p.stat().st_size) == (head, tail, size)

    def test_small_file(self):
        head, tail, size = device_output.head_tail(b"abc")
        assert head == b"abc" and tail == b"" and size == 3


class TestAliasRejection:
    def test_boundary_alias_never_serves_wrong_rows(self, tmp_path):
        """Two files with identical size, mtime, and 8 KB boundary
        windows but a DIFFERENT middle: the published image must serve
        only the real one — lookup's first-hit full-sha verification is
        the correctness story, not the probabilistic fingerprint."""
        import hashlib
        import os
        import jax
        import jax.numpy as jnp
        from tpumr.fs import get_filesystem
        from tpumr.mapred.jobconf import JobConf

        conf = JobConf()
        fs = get_filesystem(f"file://{tmp_path}")
        real = bytearray(os.urandom(32 * 1024))
        alias = bytearray(real)
        alias[16_000:16_016] = b"DIFFERENTPAYLOAD"   # middle-only change
        pr, pa = tmp_path / "real.bin", tmp_path / "alias.bin"
        pr.write_bytes(real)
        pa.write_bytes(alias)
        mtime = pr.stat().st_mtime
        os.utime(pa, (mtime, mtime))                 # same mtime

        rows = jax.device_put(jnp.arange(8.0).reshape(2, 4))
        head, tail, size = device_output.head_tail(bytes(real))
        device_output.publish(
            conf, rows, head, tail, size, mtime,
            full_sha=hashlib.sha1(bytes(real)).hexdigest())
        dev = jax.devices()[0]
        # identical fingerprints by construction
        ha, ta, sa = device_output.head_tail(bytes(alias))
        assert device_output.fingerprint(ha, ta, sa, mtime) == \
            device_output.fingerprint(head, tail, size, mtime)
        # alias rejected; the real file verifies and serves
        assert device_output.lookup(conf, dev, fs, f"file://{pa}",
                                    size, mtime) is None
        got = device_output.lookup(conf, dev, fs, f"file://{pr}",
                                   size, mtime)
        assert got is not None and got.shape == (2, 4)


class TestOfferClaim:
    def test_roundtrip_and_cap(self):
        device_output.offer("a1", "rows1")
        assert device_output.claim("a1") == "rows1"
        assert device_output.claim("a1") is None
        for i in range(40):                      # cap bounds stranded HBM
            device_output.offer(f"x{i}", i)
        assert device_output.claim("x0") is None
        assert device_output.claim("x39") == 39


def _write_chain_input(path: str, n: int, d: int):
    rng = np.random.default_rng(7)
    a = rng.normal(size=(n, d)).astype(np.float32)
    b = rng.normal(size=(d, d)).astype(np.float32)
    np.save(path + "/a.npy", a)
    np.save(path + "/b.npy", b)
    return a, b


class TestChainEndToEnd:
    def test_matmul_chain_consumes_resident_output(self, tmp_path):
        """Job 1: C = A @ B through the matmul kernel, dense output.
        Job 2: D = C @ B over job 1's output files — its TPU maps must
        stage ZERO bytes (C blocks are still resident) yet produce the
        right product."""
        from tpumr.core.counters import BackendCounter
        from tpumr.ops.matmul import clear_b_cache
        clear_b_cache()
        work = str(tmp_path)
        a, b = _write_chain_input(work, 64, 16)

        def mk(inp, out):
            conf = JobConf()
            conf.set_input_paths(inp)
            conf.set_output_path(out)
            conf.set_input_format(DenseInputFormat)
            conf.set_output_format(DenseNpyOutputFormat)
            conf.set("tpumr.dense.split.rows", 16)     # 4 maps
            conf.set("tpumr.matmul.b", f"file://{work}/b.npy")
            conf.set("tpumr.matmul.bf16", False)       # exact fp32 compare
            conf.set_map_kernel("matmul-block")
            conf.set_num_reduce_tasks(0)
            conf.set("tpumr.local.run.on.tpu", True)
            return conf

        r1 = run_job(mk(f"file://{work}/a.npy", f"file://{work}/c"))
        assert r1.successful
        staged1 = r1.counters.value(BackendCounter.GROUP,
                                    BackendCounter.TPU_DEVICE_BYTES_STAGED)
        assert staged1 > 0                       # job 1 really uploaded A

        r2 = run_job(mk(f"file://{work}/c", f"file://{work}/d"))
        assert r2.successful
        staged2 = r2.counters.value(BackendCounter.GROUP,
                                    BackendCounter.TPU_DEVICE_BYTES_STAGED)
        assert staged2 == 0, "job 2 re-staged despite resident C"

        # numerical truth: D == (A @ B) @ B, files concatenated in
        # part order == row order
        import glob
        parts = sorted(glob.glob(f"{work}/d/part-*.npy"))
        d_got = np.concatenate([np.load(p) for p in parts])
        np.testing.assert_allclose(d_got, (a @ b) @ b, rtol=2e-4)

    def test_chain_survives_cache_eviction(self, tmp_path):
        """With the HBM budget too small to retain outputs, job 2 falls
        back to reading the files — correctness never depends on
        residency."""
        from tpumr.ops.matmul import clear_b_cache
        clear_b_cache()
        work = str(tmp_path)
        a, b = _write_chain_input(work, 32, 8)

        def mk(inp, out):
            conf = JobConf()
            conf.set_input_paths(inp)
            conf.set_output_path(out)
            conf.set_input_format(DenseInputFormat)
            conf.set_output_format(DenseNpyOutputFormat)
            conf.set("tpumr.dense.split.rows", 16)
            conf.set("tpumr.matmul.b", f"file://{work}/b.npy")
            conf.set("tpumr.matmul.bf16", False)
            conf.set_map_kernel("matmul-block")
            conf.set_num_reduce_tasks(0)
            conf.set("tpumr.local.run.on.tpu", True)
            conf.set("tpumr.tpu.split.cache.mb", 0)   # nothing stays
            return conf

        assert run_job(mk(f"file://{work}/a.npy", f"file://{work}/c")).successful
        clear_split_caches()                           # simulate eviction
        assert run_job(mk(f"file://{work}/c", f"file://{work}/d")).successful
        import glob
        parts = sorted(glob.glob(f"{work}/d/part-*.npy"))
        d_got = np.concatenate([np.load(p) for p in parts])
        np.testing.assert_allclose(d_got, (a @ b) @ b, rtol=2e-4)
