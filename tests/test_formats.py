"""Input/output format tests ≈ reference TestTextInputFormat,
TestSequenceFileInputFormat, TestFileOutputCommitter."""

import numpy as np

from tpumr.fs import get_filesystem
from tpumr.io import sequencefile
from tpumr.mapred.input_formats import (
    BytesTextInputFormat, CombineFileInputFormat, DenseInputFormat,
    NLineInputFormat, SequenceFileInputFormat, TextInputFormat,
)
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.output_formats import FileOutputCommitter
from tpumr.mapred.split import FileSplit


def _conf(**kv):
    conf = JobConf()
    conf.set("fs.default.name", "mem:///")
    for k, v in kv.items():
        conf.set(k.replace("_", "."), v)
    return conf


def test_text_splits_cover_all_lines():
    conf = _conf()
    fs = get_filesystem("mem:///")
    lines = [f"line number {i}".encode() for i in range(1000)]
    fs.write_bytes("/in/data.txt", b"\n".join(lines) + b"\n")
    conf.set_input_paths("mem:///in")
    fmt = TextInputFormat()
    splits = fmt.get_splits(conf, 7)
    assert len(splits) > 1
    got = []
    for s in splits:
        got.extend(v for _, v in fmt.get_record_reader(s, conf))
    assert len(got) == 1000
    assert sorted(got) == sorted(line.decode() for line in lines)


def test_text_split_boundary_ownership():
    """A line crossing a split boundary is read by exactly one split."""
    conf = _conf()
    fs = get_filesystem("mem:///")
    data = b"aaaa\nbbbbbbbbbb\ncc\ndddddd\n"
    fs.write_bytes("/in/x.txt", data)
    conf.set_input_paths("mem:///in/x.txt")
    fmt = TextInputFormat()
    # force splits at awkward boundaries
    for cut in range(1, len(data) - 1):
        s1 = FileSplit([], "mem:///in/x.txt", 0, cut)
        s2 = FileSplit([], "mem:///in/x.txt", cut, len(data) - cut)
        vals = [v for _, v in fmt.get_record_reader(s1, conf)]
        vals += [v for _, v in fmt.get_record_reader(s2, conf)]
        assert vals == ["aaaa", "bbbbbbbbbb", "cc", "dddddd"], f"cut={cut}"


def test_text_read_batch_matches_line_reader_at_every_cut():
    """The vectorized whole-split read_batch must own exactly the lines
    the LineRecordReader owns, at every possible split boundary —
    including CRLF endings, empty lines, and a missing final newline."""
    conf = _conf()
    fs = get_filesystem("mem:///")
    for name, data in [
        ("plain", b"aaaa\nbbbbbbbbbb\ncc\ndddddd\n"),
        ("crlf", b"aa\r\nbb\r\n\r\ncc\r\n"),
        ("empty-lines", b"\n\na\n\nb\n\n"),
        ("no-final-nl", b"aaa\nbb\nclosing-line"),
        ("cr-run", b"x\r\r\ny\n"),
    ]:
        path = f"/rb/{name}.txt"
        fs.write_bytes(path, data)
        fmt = TextInputFormat()
        for cut in range(1, len(data)):
            batches = []
            readers = []
            for s in (FileSplit([], f"mem://{path}", 0, cut),
                      FileSplit([], f"mem://{path}", cut, len(data) - cut)):
                b = fmt.read_batch(s, conf)
                batches.extend(b.value(i) for i in range(b.num_records))
                readers.extend(
                    v for _, v in
                    BytesTextInputFormat().get_record_reader(s, conf))
            assert batches == readers, f"{name} cut={cut}"


def test_text_read_batch_invalid_utf8_matches_reader_semantics():
    """TextInputFormat values pass through decode(errors='replace') on
    the reader path; the batch path must produce the same bytes for
    invalid UTF-8 (and raw bytes under BytesTextInputFormat)."""
    conf = _conf()
    fs = get_filesystem("mem:///")
    data = b"caf\xe9 one\nplain two\n\xc3\xa9clair three\n"
    fs.write_bytes("/u8/x.txt", data)
    split = FileSplit([], "mem:///u8/x.txt", 0, len(data))
    batch = TextInputFormat().read_batch(split, conf)
    expect = [v.encode() for _, v in
              TextInputFormat().get_record_reader(split, conf)]
    assert [batch.value(i) for i in range(batch.num_records)] == expect
    raw = BytesTextInputFormat().read_batch(split, conf)
    assert raw.value(0) == b"caf\xe9 one"  # bytes flavor stays raw


def test_sequencefile_read_batch_mixed_block_widths():
    """Blocks that are individually fixed-width but differ across blocks
    (or single-record blocks of a ragged file) must fall back, not crash."""
    import io
    from tpumr.io import sequencefile

    for block_records, recs in [
        (3, [(b"k" * 10, b"v" * 90)] * 3 + [(b"a", b"bb")]),
        (1, [(b"k%d" % i, b"x" * (i + 1)) for i in range(5)]),
    ]:
        buf = io.BytesIO()
        w = sequencefile.Writer(buf, block_records=block_records)
        for k, v in recs:
            w.append(k, v)
        w.close()
        raw = buf.getvalue()
        r = sequencefile.Reader(io.BytesIO(raw))
        batch = r.read_batch_range(0, len(raw))
        got = [(batch.key(i), batch.value(i))
               for i in range(batch.num_records)]
        assert got == recs


def test_combine_input_read_batch_matches_reader():
    conf = _conf()
    fs = get_filesystem("mem:///")
    for i in range(5):
        fs.write_bytes(f"/cmb/f{i}.txt", f"file{i} a\nfile{i} b\n".encode())
    conf.set_input_paths("mem:///cmb")
    fmt = CombineFileInputFormat()
    splits = fmt.get_splits(conf, 2)
    for s in splits:
        batch = fmt.read_batch(s, conf)
        reader_vals = [v.encode() if isinstance(v, str) else v
                       for _, v in fmt.get_record_reader(s, conf)]
        assert [batch.value(i) for i in range(batch.num_records)] == \
            reader_vals


def test_joined_values_roundtrip():
    from tpumr.io.recordbatch import RecordBatch
    b = RecordBatch.from_values([b"alpha", b"", b"beta x", b"g"])
    assert b.joined_values() == b"alpha  beta x g"
    assert b.joined_values(0x00) == b"alpha\x00\x00beta x\x00g"
    assert RecordBatch.empty().joined_values() == b""


def test_nline_input_format():
    conf = _conf()
    fs = get_filesystem("mem:///")
    fs.write_bytes("/in/n.txt", b"".join(f"r{i}\n".encode() for i in range(10)))
    conf.set_input_paths("mem:///in/n.txt")
    conf.set("mapred.line.input.format.linespermap", 3)
    fmt = NLineInputFormat()
    splits = fmt.get_splits(conf, 1)
    assert len(splits) == 4  # 3+3+3+1
    sizes = [len(list(fmt.get_record_reader(s, conf))) for s in splits]
    assert sizes == [3, 3, 3, 1]


def test_sequencefile_input_format():
    conf = _conf()
    fs = get_filesystem("mem:///")
    with fs.create("/in/data.seq") as f:
        w = sequencefile.Writer(f, block_records=10)
        for i in range(500):
            w.append(i, f"value-{i}")
        w.close()
    conf.set_input_paths("mem:///in/data.seq")
    conf.set("mapred.min.split.size", 1)
    fmt = SequenceFileInputFormat()
    splits = fmt.get_splits(conf, 5)
    got = []
    for s in splits:
        got.extend(fmt.get_record_reader(s, conf))
    assert len(got) == 500
    assert sorted(k for k, _ in got) == list(range(500))


def test_combine_input_format():
    conf = _conf()
    fs = get_filesystem("mem:///")
    for i in range(20):
        fs.write_bytes(f"/many/f{i:02d}.txt", f"data{i}\n".encode())
    conf.set_input_paths("mem:///many")
    conf.set("mapred.max.split.size", 30)
    fmt = CombineFileInputFormat()
    splits = fmt.get_splits(conf, 1)
    assert 1 < len(splits) < 20
    got = [v for s in splits for _, v in fmt.get_record_reader(s, conf)]
    assert len(got) == 20


def test_dense_input_format():
    conf = _conf()
    fs = get_filesystem("mem:///")
    arr = np.arange(40, dtype=np.float32).reshape(10, 4)
    import io
    buf = io.BytesIO()
    np.save(buf, arr)
    fs.write_bytes("/dense/pts.npy", buf.getvalue())
    conf.set_input_paths("mem:///dense/pts.npy")
    conf.set("tpumr.dense.split.rows", 4)
    fmt = DenseInputFormat()
    splits = fmt.get_splits(conf, 1)
    assert [s.num_rows for s in splits] == [4, 4, 2]
    batch = fmt.read_batch(splits[1], conf)
    np.testing.assert_array_equal(batch.values, arr[4:8])
    assert batch.ids.tolist() == [4, 5, 6, 7]
    # CPU fallback reader
    rows = list(fmt.get_record_reader(splits[2], conf))
    assert rows[0][0] == 8 and rows[1][0] == 9


def test_output_committer_speculative_and_abort():
    conf = _conf()
    conf.set("mapred.output.dir", "mem:///out")
    fs = get_filesystem("mem:///")
    c = FileOutputCommitter(conf)
    c.setup_job()
    # two speculative attempts of the same task write the same file name
    wd0 = c.setup_task("attempt_x_0001_r_000000_0")
    wd1 = c.setup_task("attempt_x_0001_r_000000_1")
    fs.write_bytes(f"{wd0}/part-00000", b"winner")
    fs.write_bytes(f"{wd1}/part-00000", b"loser")
    c.commit_task("attempt_x_0001_r_000000_0")
    c.commit_task("attempt_x_0001_r_000000_1")  # duplicate is dropped
    assert fs.read_bytes("mem:///out/part-00000") == b"winner"
    # aborted attempt leaves nothing
    wd2 = c.setup_task("attempt_x_0001_r_000001_0")
    fs.write_bytes(f"{wd2}/part-00001", b"junk")
    c.abort_task("attempt_x_0001_r_000001_0")
    c.commit_job()
    names = [s.path.name for s in fs.list_files("mem:///out")]
    assert "part-00001" not in names
    assert "_SUCCESS" in names


def test_keyvalue_text_input_format(tmp_path):
    """≈ KeyValueTextInputFormat: first-separator split, custom separator,
    separator-less lines become (line, '')."""
    from tpumr.mapred.input_formats import (FileSplit,
                                            KeyValueTextInputFormat)
    from tpumr.mapred.jobconf import JobConf

    src = tmp_path / "kv.txt"
    src.write_bytes(b"k1\tv1\nk2\tv2a\tv2b\nbare\nk3\tv3\n")
    conf = JobConf()
    fmt = KeyValueTextInputFormat()
    split = FileSplit(path=f"file://{src}", start=0,
                      split_length=src.stat().st_size)
    recs = list(fmt.get_record_reader(split, conf))
    assert recs == [("k1", "v1"), ("k2", "v2a\tv2b"), ("bare", ""),
                    ("k3", "v3")]

    conf.set("key.value.separator.in.input.line", ",")
    src.write_bytes(b"a,1\nb,2\n")
    split = FileSplit(path=f"file://{src}", start=0,
                      split_length=src.stat().st_size)
    assert list(fmt.get_record_reader(split, conf)) == [("a", "1"),
                                                        ("b", "2")]
