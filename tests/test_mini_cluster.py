"""Mini-cluster integration ≈ TestMiniMRWithDFS: real master + trackers +
RPC + heartbeats + shuffle in one process (SURVEY.md §4.2)."""

import time

import numpy as np
import pytest

from tpumr.core.counters import BackendCounter
from tpumr.fs import get_filesystem
from tpumr.mapred.job_client import JobClient
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.mini_cluster import MiniMRCluster




class WordCountMapper:
    def configure(self, conf):
        pass

    def map(self, key, value, output, reporter):
        for w in value.split():
            output.collect(w, 1)

    def close(self):
        pass


class SumReducer:
    def configure(self, conf):
        pass

    def reduce(self, key, values, output, reporter):
        output.collect(key, sum(values))

    def close(self):
        pass


@pytest.fixture(scope="module")
def cluster():
    with MiniMRCluster(num_trackers=2, cpu_slots=2, tpu_slots=1) as c:
        yield c


def test_distributed_wordcount(cluster):
    fs = get_filesystem("mem:///")
    fs.write_bytes("/dist/in.txt", b"alpha beta\nbeta gamma\n" * 200)
    conf = cluster.create_job_conf()
    conf.set_input_paths("mem:///dist/in.txt")
    conf.set_output_path("mem:///dist/out")
    conf.set_class("mapred.mapper.class", WordCountMapper)
    conf.set_class("mapred.reducer.class", SumReducer)
    conf.set_num_reduce_tasks(2)
    conf.set("mapred.map.tasks", 3)
    conf.set("mapred.min.split.size", 1)

    result = JobClient(conf).run_job(conf)
    assert result.successful
    out = {}
    for st in fs.list_files("mem:///dist/out"):
        if st.path.name.startswith("part-"):
            for line in fs.read_bytes(st.path).decode().splitlines():
                k, v = line.split("\t")
                out[k] = int(v)
    assert out == {"alpha": "200" and 200, "beta": 400, "gamma": 200}


def test_hybrid_job_uses_both_backends(cluster):
    """A kernel-equipped job on a cluster with CPU and TPU slots lands maps
    on BOTH pools (the heterogeneous-parallelism contract, SURVEY.md §2.5.3)
    and every TPU attempt carries a concrete device id."""
    from tpumr.ops.kmeans import clear_centroid_cache
    clear_centroid_cache()
    fs = get_filesystem("mem:///")
    import io
    rng = np.random.default_rng(0)
    buf = io.BytesIO()
    np.save(buf, rng.normal(size=(400, 4)).astype(np.float32))
    fs.write_bytes("/hyb/points.npy", buf.getvalue())
    buf = io.BytesIO()
    np.save(buf, rng.normal(size=(3, 4)).astype(np.float32))
    fs.write_bytes("/hyb/cents.npy", buf.getvalue())

    conf = cluster.create_job_conf()
    conf.set_input_paths("mem:///hyb/points.npy")
    conf.set_output_path("mem:///hyb/out")
    conf.set("mapred.input.format.class",
             "tpumr.mapred.input_formats.DenseInputFormat")
    conf.set("tpumr.dense.split.rows", 25)  # 16 splits
    conf.set("tpumr.kmeans.centroids", "mem:///hyb/cents.npy")
    conf.set("tpumr.map.kernel", "kmeans-assign")
    conf.set("mapred.mapper.class", "tpumr.ops.kmeans.KMeansCpuMapper")
    conf.set("mapred.reducer.class",
             "tests.test_mini_cluster.CentroidReducer")
    conf.set_num_reduce_tasks(1)

    client = JobClient(conf)
    running = client.submit_job(conf)
    st = running.wait_for_completion(timeout=60)
    assert st["state"] == "SUCCEEDED", st
    assert st["finished_tpu_maps"] > 0, st
    assert st["finished_cpu_maps"] > 0, st
    assert st["finished_tpu_maps"] + st["finished_cpu_maps"] == 16
    # device ids stamped on TPU task reports (JobTracker.java:3414-3433)
    reports = running.task_reports("map")
    tpu_reports = [r for r in reports if r["run_on_tpu"]]
    assert tpu_reports and all(r["tpu_device_id"] >= 0 for r in tpu_reports)
    # profiling means recorded per backend — and the CPU mean comes from
    # MEASURED vectorized batch tasks (CpuBatchMapRunner), not per-record
    # Python, so the derived acceleration factor compares two real batch
    # backends (the Shirahata accel-factor semantics made honest)
    assert st["cpu_map_mean_time"] > 0
    assert st["tpu_map_mean_time"] > 0
    counters = running.counters()
    from tpumr.core.counters import BackendCounter
    assert counters.value(BackendCounter.GROUP,
                          BackendCounter.CPU_BATCH_MAP_TASKS) == \
        st["finished_cpu_maps"]


class CentroidReducer:
    def configure(self, conf):
        pass

    def reduce(self, key, values, output, reporter):
        total, n = None, 0
        for s, c in values:
            total = s if total is None else total + s
            n += c
        output.collect(key, (total / max(1, n)).tolist())

    def close(self):
        pass


def test_heartbeat_dedupe_replays_actions():
    """A duplicate heartbeat (lost response) must replay the SAME actions,
    not assign new work (JobTracker.java:3336-3375)."""
    from tpumr.mapred.jobtracker import JobMaster
    conf = JobConf()
    master = JobMaster(conf)
    try:
        status = {"tracker_name": "t1", "host": "h", "shuffle_port": 1,
                  "max_cpu_map_slots": 2, "max_tpu_map_slots": 0,
                  "max_reduce_slots": 1, "count_cpu_map_tasks": 0,
                  "count_tpu_map_tasks": 0, "count_reduce_tasks": 0,
                  "available_tpu_devices": [], "task_statuses": []}
        master.submit_job({"mapred.reduce.tasks": 0}, [{"locations": []},
                                                       {"locations": []}])
        r1 = master.heartbeat(status, True, True, 0)
        launches1 = [a for a in r1["actions"] if a["type"] == "launch"]
        assert len(launches1) == 2
        # duplicate with the same response_id → identical replay
        r2 = master.heartbeat(status, False, True, 0)
        assert r2["actions"] == r1["actions"]
        # advancing the id gets fresh (empty — no pending maps) actions
        r3 = master.heartbeat(status, False, True, r1["response_id"])
        assert [a for a in r3["actions"] if a["type"] == "launch"] == []
    finally:
        master.stop()


def test_unknown_tracker_rejoin_contract():
    """Master-restart survival: an unknown tracker's FULL non-initial
    beat is ADOPTED (registered, in-flight work kept — never the old
    blanket reinit), while an unknown DELTA beat — which the master has
    no baseline to apply — is asked to resend the full status without
    killing anything."""
    from tpumr.mapred.jobtracker import JobMaster
    master = JobMaster(JobConf())
    try:
        status = {"tracker_name": "ghost", "host": "h", "shuffle_port": 1,
                  "max_cpu_map_slots": 1, "max_tpu_map_slots": 0,
                  "max_reduce_slots": 0, "count_cpu_map_tasks": 0,
                  "count_tpu_map_tasks": 0, "count_reduce_tasks": 0,
                  "available_tpu_devices": [], "task_statuses": []}
        delta = {"tracker_name": "ghost2", "delta": True,
                 "task_statuses": []}
        resp = master.heartbeat(dict(delta), False, True, 5)
        assert resp["actions"] == [{"type": "resend_full"}]
        assert "ghost2" not in master.trackers
        resp = master.heartbeat(dict(status), False, True, 5)
        assert not [a for a in resp["actions"]
                    if a["type"] in ("reinit", "resend_full")]
        assert "ghost" in master.trackers
        assert master.metrics.snapshot()["jobtracker"][
            "trackers_adopted"] == 1
    finally:
        master.stop()


def test_commit_gate_first_wins():
    from tpumr.mapred.jobtracker import JobMaster
    master = JobMaster(JobConf())
    try:
        assert master.can_commit("task_x_0001_r_000000", "attempt_a")
        assert not master.can_commit("task_x_0001_r_000000", "attempt_b")
        assert master.can_commit("task_x_0001_r_000000", "attempt_a")
    finally:
        master.stop()


def test_failing_job_reports_failure(cluster):
    fs = get_filesystem("mem:///")
    fs.write_bytes("/fail/in.txt", b"x\n")
    conf = cluster.create_job_conf()
    conf.set_input_paths("mem:///fail/in.txt")
    conf.set_output_path("mem:///fail/out")
    conf.set("mapred.mapper.class", "tests.test_mini_cluster.BoomMapper")
    conf.set("mapred.map.max.attempts", 2)
    conf.set_num_reduce_tasks(0)
    with pytest.raises(RuntimeError, match="FAILED"):
        JobClient(conf).run_job(conf)


class BoomMapper:
    def configure(self, conf):
        pass

    def map(self, key, value, output, reporter):
        raise RuntimeError("kaboom")

    def close(self):
        pass


def test_concurrent_profiled_tasks_serialize():
    """cProfile's sys.monitoring slot is process-global (3.12): two
    attempts profiling at once must serialize, not fail with 'Another
    profiling tool is already active'."""
    import threading

    from tpumr.mapred.ids import JobID, TaskAttemptID, TaskID
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.profiler import maybe_profile
    from tpumr.mapred.task import Task

    conf = JobConf()
    conf.set("mapred.task.profile", True)
    conf.set("mapred.task.profile.maps", "0-9")
    errors = []

    def run(i, tmp):
        task = Task(TaskAttemptID(TaskID(JobID("prof", 1), True, i), 0),
                    partition=i)
        try:
            maybe_profile(conf, task, tmp, lambda: sum(range(20000)))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        threads = [threading.Thread(target=run, args=(i, f"{tmp}/{i}"))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errors, errors


def test_task_profiling_opt_in(cluster, tmp_path):
    """≈ mapred.task.profile*: opted-in tasks dump cProfile reports next
    to their attempt files; the tracker lists and serves them; tasks
    outside the range (and jobs not opting in) produce none."""
    fs = get_filesystem("mem:///")
    fs.write_bytes("/prof/in.txt", b"p q p\nq r p\n" * 50)
    conf = cluster.create_job_conf()
    conf.set_input_paths("mem:///prof/in.txt")
    conf.set_output_path("mem:///prof/out")
    conf.set_class("mapred.mapper.class", WordCountMapper)
    conf.set_class("mapred.reducer.class", SumReducer)
    conf.set("mapred.map.tasks", 4)
    conf.set("mapred.min.split.size", 1)
    conf.set_num_reduce_tasks(1)
    conf.set("mapred.task.profile", True)
    conf.set("mapred.task.profile.maps", "0-1")   # sample, not everything
    conf.set("mapred.task.profile.reduces", "0")

    result = JobClient(conf).run_job(conf)
    assert result.successful

    profiles = [aid for t in cluster.trackers for aid in t.list_profiles()]
    maps = [a for a in profiles if "_m_" in a]
    reduces = [a for a in profiles if "_r_" in a]
    assert maps, "no map profiles written"
    assert reduces, "no reduce profile written"
    # range respected: only map partitions 0-1
    assert all(int(a.split("_")[4]) <= 1 for a in maps), maps
    # content is a pstats report mentioning the map runner
    tracker = next(t for t in cluster.trackers
                   if t.list_profiles())
    text = tracker.get_profile(tracker.list_profiles()[0])
    assert "cumulative" in text or "function calls" in text
    with pytest.raises(KeyError):
        tracker.get_profile("attempt_0_0000_m_000099_0")
