"""End-to-end single-process jobs ≈ the reference's LocalJobRunner tier +
MapOutputBuffer spill semantics (SURVEY.md §4.3, MapTask.java:1396)."""

import pytest

from tpumr.core.counters import JobCounter, TaskCounter
from tpumr.fs import get_filesystem
from tpumr.mapred import JobConf, Mapper, Reducer, run_job
from tpumr.mapred.api import RawComparator, Reporter
from tpumr.mapred.map_task import MapOutputBuffer


class WordCountMapper(Mapper):
    def map(self, key, value, output, reporter):
        for w in value.split():
            output.collect(w, 1)


class SumReducer(Reducer):
    def reduce(self, key, values, output, reporter):
        output.collect(key, sum(values))


TEXT = """the quick brown fox
jumps over the lazy dog
the dog barks
"""


def _wordcount_conf(reduces=2, **extra):
    conf = JobConf()
    fs = get_filesystem("mem:///")
    fs.write_bytes("/in/text.txt", TEXT.encode() * 50)
    conf.set_input_paths("mem:///in")
    conf.set_output_path("mem:///out")
    conf.set_mapper_class(WordCountMapper)
    conf.set_reducer_class(SumReducer)
    conf.set_num_reduce_tasks(reduces)
    conf.set("mapred.map.tasks", 4)
    conf.set("mapred.min.split.size", 1)
    for k, v in extra.items():
        conf.set(k, v)
    return conf


def _read_output(path="mem:///out"):
    fs = get_filesystem("mem:///")
    out = {}
    for st in fs.list_files(path):
        if st.path.name.startswith("part-"):
            for line in fs.read_bytes(st.path).decode().splitlines():
                k, v = line.split("\t")
                assert k not in out, f"duplicate key {k} across partitions"
                out[k] = int(v)
    return out


def test_wordcount_end_to_end():
    result = run_job(_wordcount_conf())
    assert result.successful
    out = _read_output()
    assert out["the"] == 150
    assert out["dog"] == 100
    assert out["fox"] == 50
    assert result.num_maps >= 2
    c = result.counters
    assert c.value(TaskCounter.FRAMEWORK_GROUP, TaskCounter.MAP_INPUT_RECORDS) == 150
    assert c.value(JobCounter.GROUP, JobCounter.LAUNCHED_MAP_TASKS) == result.num_maps
    assert c.value(JobCounter.GROUP, JobCounter.LAUNCHED_REDUCE_TASKS) == 2


def test_wordcount_with_combiner_and_spills():
    conf = _wordcount_conf(reduces=1)
    conf.set_combiner_class(SumReducer)
    conf.set("io.sort.mb", 1)
    conf.set("io.sort.spill.percent", 0.0001)  # force many spills
    result = run_job(conf)
    assert result.successful
    out = _read_output()
    assert out["the"] == 150
    spilled = result.counters.value(TaskCounter.FRAMEWORK_GROUP,
                                    TaskCounter.SPILLED_RECORDS)
    assert spilled > 0
    combined = result.counters.value(TaskCounter.FRAMEWORK_GROUP,
                                     TaskCounter.COMBINE_INPUT_RECORDS)
    assert combined > 0


def test_wordcount_parallel_maps():
    conf = _wordcount_conf(reduces=2)
    conf.set("mapred.local.map.tasks.maximum", 4)
    result = run_job(conf)
    assert result.successful
    assert _read_output()["the"] == 150


def test_map_only_job():
    class UpperMapper(Mapper):
        def map(self, key, value, output, reporter):
            output.collect(None, value.upper())

    conf = JobConf()
    fs = get_filesystem("mem:///")
    fs.write_bytes("/in/t.txt", b"hello\nworld\n")
    conf.set_input_paths("mem:///in")
    conf.set_output_path("mem:///out-maponly")
    conf.set_mapper_class(UpperMapper)
    conf.set_num_reduce_tasks(0)
    result = run_job(conf)
    assert result.successful
    data = b"".join(fs.read_bytes(s.path)
                    for s in fs.list_files("mem:///out-maponly")
                    if s.path.name.startswith("part-"))
    assert data == b"HELLO\nWORLD\n"


def test_output_exists_refused():
    conf = _wordcount_conf()
    assert run_job(conf).successful
    with pytest.raises(FileExistsError):
        run_job(_wordcount_conf())


def test_reduce_output_sorted_within_partition():
    conf = _wordcount_conf(reduces=1)
    run_job(conf)
    fs = get_filesystem("mem:///")
    lines = fs.read_bytes("mem:///out/part-00000").decode().splitlines()
    keys = [ln.split("\t")[0] for ln in lines]
    assert keys == sorted(keys)


def test_map_output_buffer_raw_comparator():
    """Byte keys + RawComparator keep byte-lexicographic order."""
    conf = JobConf()
    conf.set_output_key_comparator_class(RawComparator)
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        buf = MapOutputBuffer(conf, 1, d, Reporter())
        for k in [b"zz", b"aa", b"mm"]:
            buf.collect(k, b"v")
        path, index = buf.flush()
        from tpumr.io import ifile
        from tpumr.io.writable import deserialize
        with open(path, "rb") as f:
            keys = [deserialize(k) for k, _ in ifile.read_partition(f, index, 0)]
        assert keys == [b"aa", b"mm", b"zz"]


def test_secondary_sort_grouping():
    """Composite keys (k, sub) sort by tuple order; grouping is exact-key —
    the seam secondary sort rides on."""

    class EmitPairs(Mapper):
        def map(self, key, value, output, reporter):
            k, sub, v = value.split(",")
            output.collect((k, int(sub)), v)

    class ConcatReducer(Reducer):
        def reduce(self, key, values, output, reporter):
            output.collect(f"{key[0]}#{key[1]}", "|".join(values))

    conf = JobConf()
    fs = get_filesystem("mem:///")
    fs.write_bytes("/in2/p.txt", b"b,2,x\na,1,y\nb,1,z\na,1,w\n")
    conf.set_input_paths("mem:///in2")
    conf.set_output_path("mem:///out2")
    conf.set_mapper_class(EmitPairs)
    conf.set_reducer_class(ConcatReducer)
    conf.set_num_reduce_tasks(1)
    run_job(conf)
    lines = get_filesystem("mem:///").read_bytes("mem:///out2/part-00000").decode().splitlines()
    assert lines == ["a#1\ty|w", "b#1\tz", "b#2\tx"]


# ------------------------------------------------- MultithreadedMapRunner


class SlowIoMapper:
    """Simulates an IO-bound mapper: sleeps per record, records thread
    ids so the test can prove concurrent map() calls."""

    threads_seen: set = set()

    def configure(self, conf):
        pass

    def map(self, key, value, output, reporter):
        import threading
        import time
        SlowIoMapper.threads_seen.add(threading.get_ident())
        time.sleep(0.02)
        output.collect(value, 1)

    def close(self):
        pass


class BoomOnRecordMapper:
    def configure(self, conf):
        pass

    def map(self, key, value, output, reporter):
        if value == "boom":
            raise RuntimeError("mapper exploded")
        output.collect(value, 1)

    def close(self):
        pass


def _mt_conf(tmp_path, mapper_cls, lines):
    from tpumr.mapred.api import MultithreadedMapRunner
    from tpumr.mapred.jobconf import JobConf
    src = tmp_path / "mt-in.txt"
    src.write_bytes(("\n".join(lines) + "\n").encode())
    conf = JobConf()
    conf.set_input_paths(f"file://{src}")
    conf.set_output_path(f"file://{tmp_path}/mt-out")
    conf.set_class("mapred.mapper.class", mapper_cls)
    conf.set("mapred.reducer.class", "tpumr.examples.basic.LongSumReducer")
    conf.set_map_runner_class(MultithreadedMapRunner)
    conf.set("mapred.map.multithreadedrunner.threads", 8)
    conf.set_num_reduce_tasks(1)
    return conf


def test_multithreaded_map_runner_concurrency_and_output(tmp_path):
    """≈ lib/MultithreadedMapRunner: map() calls run on a pool inside one
    slot; output is complete and collector-serialized."""
    from tpumr.mapred.job_client import JobClient

    SlowIoMapper.threads_seen = set()
    lines = [f"w{i % 7}" for i in range(80)]
    conf = _mt_conf(tmp_path, SlowIoMapper, lines)
    result = JobClient(conf).run_job(conf)
    assert result.successful
    assert len(SlowIoMapper.threads_seen) > 1, "never ran concurrently"

    out = {}
    for name in (tmp_path / "mt-out").iterdir():
        if name.name.startswith("part-"):
            for line in name.read_text().splitlines():
                k, v = line.split("\t")
                out[k] = int(v)
    import collections
    assert out == dict(collections.Counter(lines))


def test_multithreaded_map_runner_propagates_mapper_error(tmp_path):
    from tpumr.mapred.job_client import JobClient

    conf = _mt_conf(tmp_path, BoomOnRecordMapper,
                    ["ok"] * 10 + ["boom"] + ["ok"] * 10)
    conf.set("mapred.map.max.attempts", 1)
    with pytest.raises(RuntimeError):
        JobClient(conf).run_job(conf)
