"""FileSystem SPI tests ≈ reference fs tests (src/test/org/apache/hadoop/fs/:
TestLocalFileSystem, TestPath, TestGlobPaths)."""

import pytest

from tpumr.fs import (
    FileSystem, InMemoryFileSystem, LocalFileSystem, Path, get_filesystem,
)


def test_path_parsing():
    p = Path("mem://cluster/a/b/../c")
    assert p.scheme == "mem"
    assert p.authority == "cluster"
    assert p.path == "/a/c"
    assert str(p) == "mem://cluster/a/c"
    assert p.name == "c"
    assert p.parent.path == "/a"
    assert Path("/x//y/./z").path == "/x/y/z"
    assert Path("/a", "b").path == "/a/b"


@pytest.fixture(params=["mem", "local"])
def fs_and_root(request, tmp_path):
    if request.param == "mem":
        return InMemoryFileSystem(), "/root"
    return LocalFileSystem(), str(tmp_path)


def test_fs_contract(fs_and_root):
    fs, root = fs_and_root
    f = f"{root}/dir/file.txt"
    fs.write_bytes(f, b"hello world")
    assert fs.exists(f)
    assert fs.read_bytes(f) == b"hello world"
    st = fs.get_status(f)
    assert st.length == 11 and not st.is_dir

    # listing
    fs.write_bytes(f"{root}/dir/other.txt", b"x")
    names = [s.path.name for s in fs.list_status(f"{root}/dir")]
    assert names == ["file.txt", "other.txt"]

    # rename
    assert fs.rename(f, f"{root}/dir/renamed.txt")
    assert not fs.exists(f)
    assert fs.read_bytes(f"{root}/dir/renamed.txt") == b"hello world"

    # delete
    assert fs.delete(f"{root}/dir/renamed.txt")
    assert not fs.exists(f"{root}/dir/renamed.txt")

    # mkdirs + recursive delete
    fs.mkdirs(f"{root}/deep/a/b")
    assert fs.exists(f"{root}/deep/a/b")
    fs.write_bytes(f"{root}/deep/a/b/f", b"1")
    assert fs.delete(f"{root}/deep", recursive=True)
    assert not fs.exists(f"{root}/deep/a/b/f")


def test_fs_glob(fs_and_root):
    fs, root = fs_and_root
    for name in ["part-00000", "part-00001", "_SUCCESS", "log.txt"]:
        fs.write_bytes(f"{root}/out/{name}", b"d")
    parts = fs.glob_status(f"{root}/out/part-*")
    assert [s.path.name for s in parts] == ["part-00000", "part-00001"]


def test_fs_dispatch():
    fs = get_filesystem("mem:///x")
    assert isinstance(fs, InMemoryFileSystem)
    assert get_filesystem("mem:///y") is fs  # cached per scheme+authority
    assert isinstance(get_filesystem("/local/path"), LocalFileSystem)
    FileSystem.clear_cache()
    assert get_filesystem("mem:///x") is not fs


def test_mem_block_locations():
    fs = InMemoryFileSystem()
    fs.write_bytes("/data/big", b"x" * 100)
    locs = fs.get_block_locations("/data/big", 0, 100)
    assert locs and all(loc.hosts for loc in locs)
    # deterministic
    locs2 = fs.get_block_locations("/data/big", 0, 100)
    assert [loc.hosts for loc in locs] == [loc.hosts for loc in locs2]


def test_rename_directory_mem():
    fs = InMemoryFileSystem()
    fs.write_bytes("/a/x/1", b"1")
    fs.write_bytes("/a/x/2", b"2")
    assert fs.rename("/a/x", "/b/y")
    assert fs.read_bytes("/b/y/1") == b"1"
    assert fs.read_bytes("/b/y/2") == b"2"
    assert not fs.exists("/a/x/1")


# ---------------------------------------------------- object store (gs://)


class TestObjectStoreFs:
    """≈ fs/s3native tests: flat-namespace semantics through the SPI —
    prefix directories, marker objects, copy+delete rename."""

    @pytest.fixture()
    def gs(self, tmp_path):
        from tpumr.fs import get_filesystem
        from tpumr.mapred.jobconf import JobConf
        conf = JobConf()
        conf.set("fs.gs.emulation.dir", str(tmp_path / "objstore"))
        return get_filesystem("gs://bucket/", conf)

    def test_roundtrip_list_and_implicit_dirs(self, gs):
        gs.write_bytes("gs://bucket/data/part-0", b"alpha")
        gs.write_bytes("gs://bucket/data/part-1", b"beta")
        gs.write_bytes("gs://bucket/top.txt", b"t")
        assert gs.read_bytes("gs://bucket/data/part-0") == b"alpha"
        # implicit directory from the prefix, no mkdirs ever called
        assert gs.exists("gs://bucket/data")
        st = gs.get_status("gs://bucket/data")
        assert st.is_dir
        names = [s.path.name for s in gs.list_status("gs://bucket/data")]
        assert names == ["part-0", "part-1"]
        roots = {s.path.name: s.is_dir
                 for s in gs.list_status("gs://bucket/")}
        assert roots == {"data": True, "top.txt": False}

    def test_empty_dir_marker(self, gs):
        gs.mkdirs("gs://bucket/empty")
        assert gs.exists("gs://bucket/empty")
        assert gs.get_status("gs://bucket/empty").is_dir
        assert gs.list_status("gs://bucket/empty") == []

    def test_rename_prefix_copy_delete(self, gs):
        gs.write_bytes("gs://bucket/src/a", b"1")
        gs.write_bytes("gs://bucket/src/sub/b", b"2")
        assert gs.rename("gs://bucket/src", "gs://bucket/dst")
        assert not gs.exists("gs://bucket/src/a")
        assert gs.read_bytes("gs://bucket/dst/a") == b"1"
        assert gs.read_bytes("gs://bucket/dst/sub/b") == b"2"

    def test_delete_and_append_unsupported(self, gs):
        gs.write_bytes("gs://bucket/d/x", b"x")
        with pytest.raises(OSError, match="non-empty"):
            gs.delete("gs://bucket/d")
        assert gs.delete("gs://bucket/d", recursive=True)
        assert not gs.exists("gs://bucket/d/x")
        with pytest.raises(OSError, match="append"):
            gs.append("gs://bucket/d/x")

    def test_missing_backend_conf_is_actionable(self, tmp_path):
        from tpumr.fs import get_filesystem
        from tpumr.fs.filesystem import FileSystem
        from tpumr.mapred.jobconf import JobConf
        FileSystem.clear_cache()
        with pytest.raises(ValueError, match="fs.gs.emulation.dir"):
            get_filesystem("gs://bucket/", JobConf())

    def test_job_output_on_object_store(self, gs, tmp_path):
        """A whole MapReduce job with gs:// input and output — the
        committer's temp-prefix + promote pattern over flat keys."""
        from tpumr.mapred.job_client import JobClient
        from tpumr.mapred.jobconf import JobConf

        gs.write_bytes("gs://bucket/wc/in.txt", b"x y x\n" * 10)
        conf = JobConf()
        conf.set("fs.gs.emulation.dir", str(tmp_path / "objstore"))
        conf.set_input_paths("gs://bucket/wc/in.txt")
        conf.set_output_path("gs://bucket/wc/out")
        conf.set("mapred.mapper.class",
                 "tpumr.ops.wordcount.WordCountCpuMapper")
        conf.set("mapred.reducer.class",
                 "tpumr.examples.basic.LongSumReducer")
        conf.set_num_reduce_tasks(1)
        result = JobClient(conf).run_job(conf)
        assert result.successful
        out = {}
        for s in gs.list_status("gs://bucket/wc/out"):
            if s.path.name.startswith("part-"):
                for line in gs.read_bytes(s.path).decode().splitlines():
                    k, v = line.split("\t")
                    out[k] = int(v)
        assert out == {"x": 20, "y": 10}

    def test_duplicate_tfile_style_regressions(self, gs, tmp_path):
        """Review regressions: s3:// alias returns s3:// paths; distinct
        emulation dirs get distinct instances; rename into bucket root."""
        from tpumr.fs import get_filesystem
        from tpumr.mapred.jobconf import JobConf

        conf = JobConf()
        conf.set("fs.gs.emulation.dir", str(tmp_path / "objstore"))
        s3 = get_filesystem("s3://bucket/", conf)
        s3.write_bytes("s3://bucket/x/y", b"z")
        st = s3.list_status("s3://bucket/x")[0]
        assert str(st.path).startswith("s3://bucket/")

        other = JobConf()
        other.set("fs.gs.emulation.dir", str(tmp_path / "objstore2"))
        gs2 = get_filesystem("gs://bucket/", other)
        assert gs2 is not gs
        assert not gs2.exists("gs://bucket/x/y")

        gs.write_bytes("gs://bucket/deep/obj", b"o")
        assert gs.rename("gs://bucket/deep/obj", "gs://bucket/")
        assert gs.read_bytes("gs://bucket/obj") == b"o"


# ---------------------------------------------------------------- trash


class TestTrash:
    """≈ TestTrash: fs.trash.interval routes shell deletes into the
    per-user trash; checkpoints age out; -skipTrash bypasses."""

    def _shell(self, tmp_path, interval_min=60):
        from tpumr.fs.shell import FsShell
        from tpumr.mapred.jobconf import JobConf
        conf = JobConf()
        conf.set("fs.trash.interval", interval_min)
        conf.set("fs.trash.root", f"{tmp_path}/.Trash")
        import io as _io
        out = _io.StringIO()
        return FsShell(conf, default_fs=f"file://{tmp_path}",
                       out=out, err=out), conf, out

    def test_rm_moves_to_trash_and_is_restorable(self, tmp_path):
        from tpumr.fs import get_filesystem
        sh, conf, out = self._shell(tmp_path)
        victim = tmp_path / "data" / "keepme.txt"
        victim.parent.mkdir()
        victim.write_bytes(b"precious")
        assert sh.run(["-rm", f"file://{victim}"]) == 0
        assert "Moved to trash" in out.getvalue()
        assert not victim.exists()
        trashed = (tmp_path / ".Trash" / "Current"
                   / str(victim).lstrip("/"))
        assert trashed.read_bytes() == b"precious"
        # restore = rename back
        fs = get_filesystem(f"file://{tmp_path}", conf)
        assert fs.rename(f"file://{trashed}", f"file://{victim}")
        assert victim.read_bytes() == b"precious"

    def test_skip_trash_really_deletes(self, tmp_path):
        sh, conf, out = self._shell(tmp_path)
        victim = tmp_path / "gone.txt"
        victim.write_bytes(b"x")
        assert sh.run(["-rm", "-skipTrash", f"file://{victim}"]) == 0
        assert "Deleted" in out.getvalue()
        assert not victim.exists()
        assert not (tmp_path / ".Trash").exists()

    def test_trash_disabled_deletes_outright(self, tmp_path):
        sh, conf, out = self._shell(tmp_path, interval_min=0)
        victim = tmp_path / "plain.txt"
        victim.write_bytes(b"x")
        assert sh.run(["-rm", f"file://{victim}"]) == 0
        assert "Deleted" in out.getvalue()
        assert not (tmp_path / ".Trash").exists()

    def test_checkpoint_expiry_and_expunge(self, tmp_path):
        import time as _time

        from tpumr.fs import get_filesystem
        from tpumr.fs.trash import Trash
        from tpumr.mapred.jobconf import JobConf
        conf = JobConf()
        conf.set("fs.trash.interval", 1)  # 1 minute
        conf.set("fs.trash.root", f"{tmp_path}/.Trash")
        fs = get_filesystem(f"file://{tmp_path}", conf)
        trash = Trash(fs, conf, user="tester")
        f = tmp_path / "old.txt"
        f.write_bytes(b"old")
        assert trash.move_to_trash(f"file://{f}")
        stamp = trash.checkpoint()
        assert stamp is not None
        # young checkpoint survives expunge
        assert trash.expunge() == 0
        # age it past the interval by renaming to an old timestamp
        old = str(int(_time.time() - 120))
        fs.rename(stamp, trash.trash_root(stamp).child(old))
        assert trash.expunge() == 1
        # deleting a path already IN trash never re-trashes
        g = tmp_path / "g.txt"
        g.write_bytes(b"g")
        assert trash.move_to_trash(f"file://{g}")
        inside = trash.trash_root(f"file://{g}").child("Current")
        assert trash.move_to_trash(inside) is False
        # ... but a dir merely NAMED .Trash elsewhere is ordinary data
        other = tmp_path / "backups" / ".Trash"
        other.mkdir(parents=True)
        (other / "notes.txt").write_bytes(b"keep")
        assert trash.move_to_trash(f"file://{other}/notes.txt") is True

    def test_expunge_all_via_shell(self, tmp_path):
        sh, conf, out = self._shell(tmp_path)
        victim = tmp_path / "v.txt"
        victim.write_bytes(b"v")
        assert sh.run(["-rm", f"file://{victim}"]) == 0
        assert sh.run(["-expunge"]) == 0
        assert "Expunged 1" in out.getvalue()
        troot = tmp_path / ".Trash"
        names = [p.name for p in troot.iterdir()] if troot.exists() else []
        assert "Current" not in names
        assert not any(n.isdigit() for n in names)
