"""FileSystem SPI tests ≈ reference fs tests (src/test/org/apache/hadoop/fs/:
TestLocalFileSystem, TestPath, TestGlobPaths)."""

import pytest

from tpumr.fs import (
    FileSystem, InMemoryFileSystem, LocalFileSystem, Path, get_filesystem,
)


def test_path_parsing():
    p = Path("mem://cluster/a/b/../c")
    assert p.scheme == "mem"
    assert p.authority == "cluster"
    assert p.path == "/a/c"
    assert str(p) == "mem://cluster/a/c"
    assert p.name == "c"
    assert p.parent.path == "/a"
    assert Path("/x//y/./z").path == "/x/y/z"
    assert Path("/a", "b").path == "/a/b"


@pytest.fixture(params=["mem", "local"])
def fs_and_root(request, tmp_path):
    if request.param == "mem":
        return InMemoryFileSystem(), "/root"
    return LocalFileSystem(), str(tmp_path)


def test_fs_contract(fs_and_root):
    fs, root = fs_and_root
    f = f"{root}/dir/file.txt"
    fs.write_bytes(f, b"hello world")
    assert fs.exists(f)
    assert fs.read_bytes(f) == b"hello world"
    st = fs.get_status(f)
    assert st.length == 11 and not st.is_dir

    # listing
    fs.write_bytes(f"{root}/dir/other.txt", b"x")
    names = [s.path.name for s in fs.list_status(f"{root}/dir")]
    assert names == ["file.txt", "other.txt"]

    # rename
    assert fs.rename(f, f"{root}/dir/renamed.txt")
    assert not fs.exists(f)
    assert fs.read_bytes(f"{root}/dir/renamed.txt") == b"hello world"

    # delete
    assert fs.delete(f"{root}/dir/renamed.txt")
    assert not fs.exists(f"{root}/dir/renamed.txt")

    # mkdirs + recursive delete
    fs.mkdirs(f"{root}/deep/a/b")
    assert fs.exists(f"{root}/deep/a/b")
    fs.write_bytes(f"{root}/deep/a/b/f", b"1")
    assert fs.delete(f"{root}/deep", recursive=True)
    assert not fs.exists(f"{root}/deep/a/b/f")


def test_fs_glob(fs_and_root):
    fs, root = fs_and_root
    for name in ["part-00000", "part-00001", "_SUCCESS", "log.txt"]:
        fs.write_bytes(f"{root}/out/{name}", b"d")
    parts = fs.glob_status(f"{root}/out/part-*")
    assert [s.path.name for s in parts] == ["part-00000", "part-00001"]


def test_fs_dispatch():
    fs = get_filesystem("mem:///x")
    assert isinstance(fs, InMemoryFileSystem)
    assert get_filesystem("mem:///y") is fs  # cached per scheme+authority
    assert isinstance(get_filesystem("/local/path"), LocalFileSystem)
    FileSystem.clear_cache()
    assert get_filesystem("mem:///x") is not fs


def test_mem_block_locations():
    fs = InMemoryFileSystem()
    fs.write_bytes("/data/big", b"x" * 100)
    locs = fs.get_block_locations("/data/big", 0, 100)
    assert locs and all(loc.hosts for loc in locs)
    # deterministic
    locs2 = fs.get_block_locations("/data/big", 0, 100)
    assert [loc.hosts for loc in locs] == [loc.hosts for loc in locs2]


def test_rename_directory_mem():
    fs = InMemoryFileSystem()
    fs.write_bytes("/a/x/1", b"1")
    fs.write_bytes("/a/x/2", b"2")
    assert fs.rename("/a/x", "/b/y")
    assert fs.read_bytes("/b/y/1") == b"1"
    assert fs.read_bytes("/b/y/2") == b"2"
    assert not fs.exists("/a/x/1")
