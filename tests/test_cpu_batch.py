"""Vectorized CPU batch map path (CpuBatchMapRunner + map_batch_cpu):
CPU slots of kernel jobs process whole staged splits in numpy instead of
per-record Python — the reference's hybrid premise (CPU slots carry real
work, JobQueueTaskScheduler.java:127-178) made honest."""

import numpy as np

from tpumr.core.counters import BackendCounter
from tpumr.examples.basic import save_npy as _save_npy
from tpumr.fs import get_filesystem
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.local_runner import run_job


class TestNumpyKernel:
    def test_matches_device_path(self):
        from tpumr.ops.kmeans import (assign_and_partials,
                                      assign_and_partials_numpy)
        rng = np.random.default_rng(1)
        pts = rng.normal(size=(5000, 6)).astype(np.float32)
        cents = rng.normal(size=(9, 6)).astype(np.float32)
        _a, dev_sums, dev_counts = assign_and_partials(pts, cents)
        sums, counts = assign_and_partials_numpy(pts, cents, chunk=700)
        np.testing.assert_array_equal(counts, np.asarray(dev_counts))
        np.testing.assert_allclose(sums, np.asarray(dev_sums), rtol=1e-4)

    def test_throughput_is_batch_speed(self):
        """The point of the path: the per-record loop measured ~34k rec/s;
        the batch path should clear a GENEROUS floor even on a loaded CI
        host (bench.py reports the real multi-M rec/s number)."""
        import time
        from tpumr.ops.kmeans import assign_and_partials_numpy
        rng = np.random.default_rng(2)
        pts = rng.normal(size=(1_000_000, 8)).astype(np.float32)
        cents = rng.normal(size=(16, 8)).astype(np.float32)
        assign_and_partials_numpy(pts[:1000], cents)  # warm caches
        t0 = time.time()
        assign_and_partials_numpy(pts, cents)
        rate = pts.shape[0] / (time.time() - t0)
        assert rate >= 200_000, f"CPU batch rate {rate:.0f} rec/s — " \
            "batch path appears to have regressed to per-record speed"


class TestCpuBatchJobs:
    def _kmeans_conf(self, tag: str, batch: bool) -> JobConf:
        fs = get_filesystem("mem:///")
        rng = np.random.default_rng(5)
        _save_npy(fs, f"/cb/{tag}/pts.npy",
                  rng.normal(size=(600, 4)).astype(np.float32))
        _save_npy(fs, f"/cb/{tag}/cents.npy",
                  rng.normal(size=(3, 4)).astype(np.float32))
        conf = JobConf()
        conf.set_input_paths(f"mem:///cb/{tag}/pts.npy")
        conf.set_output_path(f"mem:///cb/{tag}/out")
        conf.set("mapred.input.format.class",
                 "tpumr.mapred.input_formats.DenseInputFormat")
        conf.set("tpumr.dense.split.rows", 150)
        conf.set("tpumr.kmeans.centroids", f"mem:///cb/{tag}/cents.npy")
        conf.set_map_kernel("kmeans-assign")
        conf.set("mapred.mapper.class", "tpumr.ops.kmeans.KMeansCpuMapper")
        conf.set("mapred.reducer.class",
                 "tests.test_mini_cluster.CentroidReducer")
        conf.set_num_reduce_tasks(1)
        if not batch:
            conf.set("tpumr.cpu.batch.map", False)
        return conf

    def test_kernel_job_on_cpu_uses_batch_runner(self):
        from tpumr.ops.kmeans import clear_centroid_cache
        clear_centroid_cache()
        result = run_job(self._kmeans_conf("batch", batch=True))
        assert result.successful
        assert result.counters.value(
            BackendCounter.GROUP, BackendCounter.CPU_BATCH_MAP_TASKS) == 4
        # and no TPU task ran (local runner defaulted to CPU)
        assert result.counters.value(
            BackendCounter.GROUP, BackendCounter.TPU_MAP_TASKS) == 0

    def test_batch_and_per_record_agree(self):
        """Same job, batch path vs per-record opt-out: identical reduce
        output (the batch path is an optimization, not a semantic change)."""
        from tpumr.ops.kmeans import clear_centroid_cache
        fs = get_filesystem("mem:///")

        clear_centroid_cache()
        assert run_job(self._kmeans_conf("a", batch=True)).successful
        clear_centroid_cache()
        r2 = run_job(self._kmeans_conf("b", batch=False))
        assert r2.successful
        assert r2.counters.value(
            BackendCounter.GROUP, BackendCounter.CPU_BATCH_MAP_TASKS) == 0

        def read_out(tag):
            out = {}
            for st in fs.list_status(f"/cb/{tag}/out"):
                if st.path.name.startswith("part-"):
                    for line in fs.read_bytes(st.path).decode().splitlines():
                        k, _, v = line.partition("\t")
                        out[k] = v
            return out

        a, b = read_out("a"), read_out("b")
        assert a.keys() == b.keys()
        for k in a:
            va = np.asarray(eval(a[k]))  # noqa: S307 — test-local literals
            vb = np.asarray(eval(b[k]))
            np.testing.assert_allclose(va, vb, rtol=1e-4)

    def test_wordcount_kernel_cpu_batch(self):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/cbw/in.txt", b"alpha beta alpha\ngamma beta alpha\n")
        conf = JobConf()
        conf.set_input_paths("mem:///cbw/in.txt")
        conf.set_output_path("mem:///cbw/out")
        conf.set_map_kernel("wordcount")
        conf.set("mapred.reducer.class",
                 "tpumr.examples.basic.LongSumReducer")
        conf.set_num_reduce_tasks(1)
        result = run_job(conf)
        assert result.successful
        assert result.counters.value(
            BackendCounter.GROUP, BackendCounter.CPU_BATCH_MAP_TASKS) >= 1
        text = b"".join(fs.read_bytes(st.path)
                        for st in fs.list_status("/cbw/out")
                        if st.path.name.startswith("part-")).decode()
        counts = dict(line.split("\t") for line in text.splitlines())
        assert counts == {"alpha": "3", "beta": "2", "gamma": "1"}
