"""Pipes tier tests ≈ src/test/org/apache/hadoop/mapred/pipes/TestPipes.java:
external executables (Python and C++) speaking the binary protocol, dual
CPU/TPU executable selection, counters/partitioned output over the uplink."""

import io
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

from tpumr.fs import get_filesystem
from tpumr.mapred.jobconf import JobConf
from tpumr.pipes import Submitter
from tpumr.pipes import protocol as P

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_script(path: str, body: str) -> str:
    with open(path, "w") as f:
        f.write(f"#!{sys.executable}\nimport sys\n"
                f"sys.path.insert(0, {REPO!r})\n" + textwrap.dedent(body))
    os.chmod(path, 0o755)
    return path


WORDCOUNT = """
    from tpumr.pipes import child

    class M(child.Mapper):
        def __init__(self, ctx):
            self.words = ctx.get_counter("WordCount", "INPUT_WORDS")
            self.ctx = ctx

        def map(self, ctx):
            toks = ctx.input_value.split()
            for w in toks:
                ctx.emit(w, b"1")
            ctx.increment_counter(self.words, len(toks))

    class R(child.Reducer):
        def reduce(self, ctx):
            total = 0
            while ctx.next_value():
                total += int(ctx.input_value)
            ctx.emit(ctx.input_key, str(total))

    class F(child.Factory):
        def create_mapper(self, ctx):
            return M(ctx)

        def create_reducer(self, ctx):
            return R()

    raise SystemExit(child.run_task(F()))
"""

DEVICE_PROBE = """
    from tpumr.pipes import child

    device = sys.argv[1] if len(sys.argv) > 1 else "none"

    class M(child.Mapper):
        def map(self, ctx):
            ctx.emit(ctx.input_value, "dev=" + device)

    class R(child.Reducer):
        def reduce(self, ctx):
            while ctx.next_value():
                ctx.emit(ctx.input_key, ctx.input_value)

    class F(child.Factory):
        def create_mapper(self, ctx):
            return M()

        def create_reducer(self, ctx):
            return R()

    raise SystemExit(child.run_task(F()))
"""


def _read_output(fs, out_dir):
    merged = {}
    for st in fs.list_files(out_dir):
        if st.path.name.startswith("part-"):
            for line in fs.read_bytes(st.path).decode().splitlines():
                k, _, v = line.partition("\t")
                merged[k] = v
    return merged


def test_varint_roundtrip():
    buf = io.BytesIO()
    for n in (0, 1, 127, 128, 300, 2**21, 2**40):
        P.write_varint(buf, n)
    buf.seek(0)
    for n in (0, 1, 127, 128, 300, 2**21, 2**40):
        assert P.read_varint(buf) == n


def test_pipes_wordcount_python_child(tmp_path):
    prog = _write_script(str(tmp_path / "wc.py"), WORDCOUNT)
    fs = get_filesystem("mem:///")
    fs.write_bytes("/pipes/in.txt", b"a b a\nc b a\n" * 30)

    conf = JobConf()
    conf.set_input_paths("mem:///pipes/in.txt")
    conf.set_output_path("mem:///pipes/out")
    conf.set_num_reduce_tasks(1)
    conf.set("tpumr.cache.dir", str(tmp_path / "cache"))
    Submitter.set_executable(conf, prog)
    result = Submitter.run_job(conf)
    assert result.successful
    out = _read_output(fs, "mem:///pipes/out")
    assert out == {"a": "90", "b": "60", "c": "30"}
    # child counters reached the framework (REGISTER/INCREMENT_COUNTER)
    assert result.counters.value("WordCount", "INPUT_WORDS") == 180


def test_pipes_dual_executable_tpu_selection(tmp_path):
    """run_on_tpu picks cache slot 1 and passes the device id as argv[1]
    (Application.java:162-181 semantics)."""
    cpu = _write_script(str(tmp_path / "cpu.py"), DEVICE_PROBE)
    tpu = _write_script(str(tmp_path / "tpu.py"), DEVICE_PROBE)
    fs = get_filesystem("mem:///")
    fs.write_bytes("/dual/in.txt", b"r1\nr2\n")

    conf = JobConf()
    conf.set_input_paths("mem:///dual/in.txt")
    conf.set_output_path("mem:///dual/out")
    conf.set_num_reduce_tasks(1)
    conf.set("tpumr.cache.dir", str(tmp_path / "cache"))
    conf.set("tpumr.local.run.on.tpu", True)
    Submitter.set_executable(conf, cpu)
    Submitter.set_tpu_executable(conf, tpu)
    result = Submitter.run_job(conf)
    assert result.successful
    out = _read_output(fs, "mem:///dual/out")
    # device id 0 (the local runner's TPU slot) arrived as argv[1]
    assert out == {"r1": "dev=0", "r2": "dev=0"}


def test_pipes_distributed_hybrid(tmp_path):
    """Dual-executable pipes job on a real mini-cluster: the TPU pipes
    executable makes the job accelerator-eligible (the
    hadoop.pipes.gpu.executable gate) and TPU attempts run slot-1 binaries
    with device ids."""
    from tpumr.mapred.job_client import JobClient
    from tpumr.mapred.mini_cluster import MiniMRCluster

    cpu = _write_script(str(tmp_path / "cpu.py"), DEVICE_PROBE)
    tpu = _write_script(str(tmp_path / "tpu.py"), DEVICE_PROBE)
    fs = get_filesystem("mem:///")
    data = "".join(f"rec{i:03d}\n" for i in range(12)).encode()
    fs.write_bytes("/dh/in.txt", data)

    with MiniMRCluster(num_trackers=1, cpu_slots=1, tpu_slots=1) as cluster:
        conf = cluster.create_job_conf()
        conf.set_input_paths("mem:///dh/in.txt")
        conf.set_output_path("mem:///dh/out")
        conf.set_num_reduce_tasks(1)
        conf.set("mapred.map.tasks", 6)
        conf.set("mapred.min.split.size", 1)
        from tpumr.pipes.submitter import setup_pipes_job
        Submitter.set_executable(conf, cpu)
        Submitter.set_tpu_executable(conf, tpu)
        setup_pipes_job(conf)
        client = JobClient(conf)
        running = client.submit_job(conf)
        st = running.wait_for_completion(timeout=120)
        assert st["state"] == "SUCCEEDED", st
        assert st["finished_tpu_maps"] > 0, st
        out = _read_output(fs, "mem:///dh/out")
        assert len(out) == 12
        assert any(v.startswith("dev=") and v != "dev=none"
                   for v in out.values())


@pytest.fixture(scope="module")
def cpp_wordcount(cpp_examples):
    return os.path.join(cpp_examples, "wordcount")


def test_pipes_wordcount_cpp_child(cpp_wordcount, tmp_path):
    """The C++ child runtime end-to-end (≈ TestPipes with the C++ demos)."""
    fs = get_filesystem("mem:///")
    fs.write_bytes("/cpp/in.txt", b"tpu mxu tpu\nici mxu tpu\n" * 10)

    conf = JobConf()
    conf.set_input_paths("mem:///cpp/in.txt")
    conf.set_output_path("mem:///cpp/out")
    conf.set_num_reduce_tasks(1)
    conf.set("tpumr.cache.dir", str(tmp_path / "cache"))
    Submitter.set_executable(conf, cpp_wordcount)
    result = Submitter.run_job(conf)
    assert result.successful
    out = _read_output(fs, "mem:///cpp/out")
    assert out == {"tpu": "30", "mxu": "20", "ici": "10"}
    assert result.counters.value("WordCount", "INPUT_WORDS") == 60


@pytest.fixture(scope="module")
def cpp_examples():
    """Build all native pipes examples once (≈ the reference's 4 demos)."""
    if shutil.which("g++") is None:
        pytest.skip("g++ not available")
    native = os.path.join(REPO, "native", "pipes")
    build = subprocess.run(["make", "-C", native], capture_output=True,
                           text=True)
    if build.returncode != 0:
        pytest.fail(f"native pipes build failed:\n{build.stderr}")
    return os.path.join(native, "build")


def test_pipes_wordcount_part_cpp_partitioner(cpp_examples, tmp_path):
    """≈ wordcount-part.cc: the CHILD routes outputs (first-byte
    partitioner → PARTITIONED_OUTPUT frames); each word must land in the
    partition its first byte selects."""
    fs = get_filesystem("mem:///")
    fs.write_bytes("/cpppart/in.txt", b"apple crumble apple\nbanana crumble\n" * 5)

    conf = JobConf()
    conf.set_input_paths("mem:///cpppart/in.txt")
    conf.set_output_path("mem:///cpppart/out")
    conf.set_num_reduce_tasks(2)
    conf.set("tpumr.cache.dir", str(tmp_path / "cache"))
    Submitter.set_executable(conf,
                             os.path.join(cpp_examples, "wordcount-part"))
    result = Submitter.run_job(conf)
    assert result.successful

    by_part = {}
    for st in fs.list_files("mem:///cpppart/out"):
        if st.path.name.startswith("part-"):
            idx = int(st.path.name.rsplit("-", 1)[1])
            for line in fs.read_bytes(st.path).decode().splitlines():
                k, v = line.split("\t")
                by_part[k] = (idx, int(v))
    assert {k: v[1] for k, v in by_part.items()} == \
        {"apple": 10, "banana": 5, "crumble": 10}
    for word, (idx, _) in by_part.items():
        assert idx == ord(word[0]) % 2, f"{word} landed in wrong partition"


def test_pipes_sort_cpp_identity(cpp_examples, tmp_path):
    """≈ sort.cc: identity child; the framework's sort/shuffle orders the
    records."""
    fs = get_filesystem("mem:///")
    lines = [f"k{97 - i:03d}" for i in range(60)]
    fs.write_bytes("/cppsort/in.txt", ("\n".join(lines) + "\n").encode())

    conf = JobConf()
    conf.set_input_paths("mem:///cppsort/in.txt")
    conf.set_output_path("mem:///cppsort/out")
    conf.set_num_reduce_tasks(1)
    conf.set("tpumr.cache.dir", str(tmp_path / "cache"))
    Submitter.set_executable(conf, os.path.join(cpp_examples, "sort"))
    result = Submitter.run_job(conf)
    assert result.successful
    out_keys = []
    for st in fs.list_files("mem:///cppsort/out"):
        if st.path.name.startswith("part-"):
            for line in fs.read_bytes(st.path).decode().splitlines():
                out_keys.append(line.split("\t")[0])
    assert out_keys == sorted(lines)


def test_pipes_wordcount_nopipe_child_reads_split(cpp_examples, tmp_path):
    """≈ wordcount-nopipe.cc: tpumr.pipes.piped.input=false — the child
    parses the split JSON and reads its own byte range; multiple splits
    must not double-count boundary lines."""
    src = tmp_path / "nopipe-in.txt"
    src.write_bytes(b"red green red\nblue green\n" * 40)

    conf = JobConf()
    conf.set_input_paths(f"file://{src}")
    conf.set_output_path(f"file://{tmp_path}/nopipe-out")
    conf.set_num_reduce_tasks(1)
    conf.set("mapred.map.tasks", 3)
    conf.set("mapred.min.split.size", 1)
    conf.set("tpumr.pipes.piped.input", False)
    conf.set("tpumr.cache.dir", str(tmp_path / "cache"))
    Submitter.set_executable(conf,
                             os.path.join(cpp_examples, "wordcount-nopipe"))
    result = Submitter.run_job(conf)
    assert result.successful
    out = {}
    for name in (tmp_path / "nopipe-out").iterdir():
        if name.name.startswith("part-"):
            for line in name.read_text().splitlines():
                k, v = line.split("\t")
                out[k] = int(v)
    assert out == {"red": 80, "green": 80, "blue": 40}


NOPIPE_PY = """
    import json
    from tpumr.pipes import child

    class M(child.Mapper):
        def __init__(self, ctx):
            self.ctx = ctx

        def map(self, ctx):
            # own-reader mode: one call, the split JSON in input_split
            split = json.loads(ctx.input_split.decode())
            path = split["path"].replace("file://", "")
            start, length = split["start"], split["split_length"]
            with open(path, "rb") as f:
                if start > 0:
                    f.seek(start - 1)
                    f.readline()  # previous split owns the partial line
                while f.tell() < start + length:
                    line = f.readline()
                    if not line:
                        break
                    for w in line.split():
                        ctx.emit(w, "1")

    class R(child.Reducer):
        def reduce(self, ctx):
            total = 0
            while ctx.next_value():
                total += int(ctx.input_value)
            ctx.emit(ctx.input_key, str(total))

    class F(child.Factory):
        def create_mapper(self, ctx):
            return M(ctx)

        def create_reducer(self, ctx):
            return R()

    raise SystemExit(child.run_task(F()))
"""


def test_pipes_nopipe_python_child(tmp_path):
    """Own-reader mode for PYTHON children too: with piped.input=false the
    child maps once over the split it reads itself — never a silent
    zero-record success."""
    prog = _write_script(str(tmp_path / "nopipe.py"), NOPIPE_PY)
    src = tmp_path / "np-in.txt"
    src.write_bytes(b"dog cat dog\ncat\n" * 30)

    conf = JobConf()
    conf.set_input_paths(f"file://{src}")
    conf.set_output_path(f"file://{tmp_path}/np-out")
    conf.set_num_reduce_tasks(1)
    conf.set("mapred.map.tasks", 2)
    conf.set("mapred.min.split.size", 1)
    conf.set("tpumr.pipes.piped.input", False)
    conf.set("tpumr.cache.dir", str(tmp_path / "cache"))
    Submitter.set_executable(conf, prog)
    result = Submitter.run_job(conf)
    assert result.successful
    out = {}
    for name in (tmp_path / "np-out").iterdir():
        if name.name.startswith("part-"):
            for line in name.read_text().splitlines():
                k, v = line.split("\t")
                out[k] = int(v)
    assert out == {"dog": 60, "cat": 60}
