"""Reduce-side data join ≈ contrib/data_join's TestDataJoin: two tagged
sources joined on a shared key through a real job, cross-product and
filter semantics, per-group truncation."""

from tpumr.contrib.datajoin import (DataJoinMapper, DataJoinReducer,
                                    make_datajoin_conf)
from tpumr.fs import get_filesystem
from tpumr.mapred import run_job


class OrderMapper(DataJoinMapper):
    def input_tag(self, conf):
        return "orders"

    def extract_key(self, key, value):
        v = value if isinstance(value, str) else value.decode()
        return v.split(",")[0]

    def extract_value(self, key, value):
        v = value if isinstance(value, str) else value.decode()
        return v.split(",", 1)[1]


class UserMapper(OrderMapper):
    def input_tag(self, conf):
        return "users"


class InnerJoin(DataJoinReducer):
    required_tags = ("orders", "users")

    def combine(self, key, tags, values, output, reporter):
        by_tag = dict(zip(tags, values))
        if by_tag["orders"].endswith("drop-me"):
            return None
        return f"{by_tag['users']}|{by_tag['orders']}"


def _write_sources(fs):
    fs.write_bytes("/dj/orders/part-0",
                   b"u1,order-a\nu1,order-b\nu2,order-c\n"
                   b"u3,order-d\nu2,drop-me\n")
    fs.write_bytes("/dj/users/part-0", b"u1,alice\nu2,bob\nu9,nobody\n")


def test_inner_join_cross_product_and_filter():
    fs = get_filesystem("mem:///")
    _write_sources(fs)
    conf = make_datajoin_conf(
        [("orders", "mem:///dj/orders", OrderMapper),
         ("users", "mem:///dj/users", UserMapper)],
        InnerJoin, "mem:///dj/out")
    conf.set_num_reduce_tasks(1)
    result = run_job(conf)
    assert result.successful
    lines = sorted(fs.read_bytes("mem:///dj/out/part-00000")
                   .decode().splitlines())
    # u1 x 2 orders, u2 x 1 (drop-me filtered), u3 has no user row,
    # u9 has no orders row
    assert lines == ["u1\talice|order-a", "u1\talice|order-b",
                     "u2\tbob|order-c"]
    assert result.counters.value("tpumr.DataJoin", "TUPLES_JOINED") == 3
    assert result.counters.value("tpumr.DataJoin", "KEYS_UNMATCHED") == 2


def test_group_truncation_bounds_cross_product():
    fs = get_filesystem("mem:///")
    fs.write_bytes("/djt/orders/part-0",
                   b"".join(b"u1,o%d\n" % i for i in range(10)))
    fs.write_bytes("/djt/users/part-0", b"u1,alice\n")

    class Join(DataJoinReducer):
        def combine(self, key, tags, values, output, reporter):
            return "|".join(values)

    conf = make_datajoin_conf(
        [("orders", "mem:///djt/orders", OrderMapper),
         ("users", "mem:///djt/users", UserMapper)],
        Join, "mem:///djt/out")
    conf.set("datajoin.maxNumOfValuesPerGroup", 4)
    conf.set_num_reduce_tasks(1)
    result = run_job(conf)
    assert result.successful
    lines = fs.read_bytes("mem:///djt/out/part-00000").decode().splitlines()
    assert len(lines) == 4  # capped at 4 orders x 1 user
    assert result.counters.value("tpumr.DataJoin", "VALUES_TRUNCATED") == 6


def test_sibling_directory_does_not_match_prefix():
    """'orders' registered for /dj2/users must NOT claim /dj2/users_extra
    (prefix matches only at a path-separator boundary)."""
    fs = get_filesystem("mem:///")
    fs.write_bytes("/dj2/users/part-0", b"u1,alice\n")
    fs.write_bytes("/dj2/users_extra/part-0", b"u1,mallory\n")
    conf = make_datajoin_conf(
        [("users", "mem:///dj2/users", UserMapper)],
        InnerJoin, "mem:///dj2/out")
    conf.set_input_paths("mem:///dj2/users", "mem:///dj2/users_extra")
    conf.set_num_reduce_tasks(1)
    import pytest
    with pytest.raises(ValueError, match="no datajoin mapper"):
        run_job(conf)


def test_unregistered_source_fails_loudly():
    fs = get_filesystem("mem:///")
    _write_sources(fs)
    conf = make_datajoin_conf(
        [("orders", "mem:///dj/orders", OrderMapper)],
        InnerJoin, "mem:///dj/out2")
    conf.set_input_paths("mem:///dj/orders", "mem:///dj/users")  # users
    conf.set_num_reduce_tasks(1)                 # path not registered
    import pytest
    with pytest.raises(ValueError, match="no datajoin mapper"):
        run_job(conf)
