"""Metrics system + HTTP status tier + history server ≈ metrics2,
HttpServer/webapps, JobHistoryServer (SURVEY.md §5)."""

import json
import time
import urllib.request

import pytest

from tpumr.fs import get_filesystem
from tpumr.metrics import FileSink, MetricsRegistry, MetricsSystem
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.mini_cluster import MiniMRCluster


def fetch(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class TestMetricsCore:
    def test_registry_counters_and_gauges(self):
        reg = MetricsRegistry("x")
        reg.incr("events")
        reg.incr("events", 4)
        reg.set_gauge("depth", lambda: 7)
        reg.set_gauge("static", 3)
        snap = reg.snapshot()
        assert snap == {"events": 5, "depth": 7, "static": 3}

    def test_broken_gauge_survives(self):
        """A failing gauge is SKIPPED and counted — never snapshotted as
        an '<error: ...>' string that numeric sinks (UdpSink, the
        Prometheus renderer) would have to dodge."""
        reg = MetricsRegistry("x")
        reg.set_gauge("bad", lambda: 1 / 0)
        reg.set_gauge("good", lambda: 7)
        snap = reg.snapshot()
        assert "bad" not in snap
        assert snap["good"] == 7
        assert snap["metrics_gauge_errors"] == 1
        reg.snapshot()
        assert reg.snapshot()["metrics_gauge_errors"] == 3
        typed = reg.typed_snapshot()
        assert "bad" not in typed["gauges"]
        assert typed["counters"]["metrics_gauge_errors"] == 4

    def test_system_publish_to_file_sink(self, tmp_path):
        ms = MetricsSystem("test", period_s=3600)
        reg = ms.new_registry("src1")
        reg.incr("n", 2)
        path = str(tmp_path / "metrics.jsonl")
        ms.add_sink(FileSink(path))
        ms.publish_once()
        rec = json.loads(open(path).read().splitlines()[0])
        assert rec["prefix"] == "test"
        assert rec["sources"]["src1"]["n"] == 2

    def test_file_sink_stamps_host_and_sequence(self, tmp_path):
        """FileSink records carry hostname + a monotonic per-sink seq so
        interleaved daemon logs (per-host files concatenated later) can
        be totally ordered — wall-clock ts alone cannot do that across
        hosts or clock steps; a seq gap is a dropped-record tell."""
        import socket
        ms = MetricsSystem("test", period_s=3600)
        ms.new_registry("src").incr("n")
        path = str(tmp_path / "m.jsonl")
        ms.add_sink(FileSink(path))
        ms.publish_once()
        ms.publish_once()
        ms.publish_once()
        recs = [json.loads(line) for line in open(path)]
        assert [r["seq"] for r in recs] == [1, 2, 3]
        assert all(r["host"] == socket.gethostname() for r in recs)


    def test_udp_sink_statsd_lines_and_conf_wiring(self, tmp_path):
        """UdpSink (the GangliaSink role): statsd gauge lines over UDP,
        numeric metrics only, MTU-bounded batching; sinks_from_conf wires
        both sink kinds from daemon conf."""
        import socket

        from tpumr.metrics import UdpSink, sinks_from_conf
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.settimeout(5)
        port = recv.getsockname()[1]

        ms = MetricsSystem("td", period_s=3600)
        reg = ms.new_registry("jt")
        reg.incr("heartbeats", 7)
        reg.set_gauge("ratio", lambda: 0.5)
        reg.set_gauge("label", lambda: "text-is-skipped")
        ms.add_sink(UdpSink("127.0.0.1", port))
        ms.publish_once()
        lines = recv.recv(65536).decode().splitlines()
        assert "td.jt.heartbeats:7|g" in lines
        assert "td.jt.ratio:0.5|g" in lines
        assert not any("label" in l for l in lines)

        # many metrics split across MTU-sized datagrams, none lost
        reg2 = ms.new_registry("big")
        for i in range(200):
            reg2.incr(f"metric_{i:03d}", i)
        ms.publish_once()
        got = []
        while len(got) < 202:
            try:
                got.extend(recv.recv(65536).decode().splitlines())
            except socket.timeout:
                break
        assert len([l for l in got if l.startswith("td.big.")]) == 200
        recv.close()

        from tpumr.mapred.jobconf import JobConf
        conf = JobConf()
        conf.set("tpumr.metrics.file", str(tmp_path / "m.jsonl"))
        conf.set("tpumr.metrics.udp", f"127.0.0.1:{port}")
        kinds = {type(s).__name__ for s in sinks_from_conf(conf)}
        assert kinds == {"FileSink", "UdpSink"}
        assert sinks_from_conf(JobConf()) == []

        # a typo'd observability knob must not kill the daemon
        for bad in ("monitor01", "monitor01:", ":notaport"):
            c = JobConf()
            c.set("tpumr.metrics.udp", bad)
            assert sinks_from_conf(c) == []


class TestHistogram:
    def test_observe_count_sum_minmax_and_percentiles(self):
        from tpumr.metrics import Histogram, exponential_bounds
        h = Histogram("lat", exponential_bounds(0.001, 2.0, 12))
        for ms in range(1, 101):          # 1..100 ms uniform
            h.observe(ms / 1000.0)
        s = h.snapshot()
        assert s["count"] == 100
        assert abs(s["sum"] - 5.05) < 1e-9
        assert s["min"] == 0.001 and s["max"] == 0.1
        # estimation error bounded by the bucket factor (2x)
        assert 0.025 <= s["p50"] <= 0.1
        assert 0.05 <= s["p95"] <= 0.2
        assert s["p50"] <= s["p95"] <= s["p99"]

    def test_bounds_validation_and_defaults(self):
        from tpumr.metrics import Histogram, exponential_bounds
        import pytest as _pytest
        with _pytest.raises(ValueError):
            exponential_bounds(0, 2, 4)
        with _pytest.raises(ValueError):
            Histogram("x", [1.0, 1.0, 2.0])
        assert Histogram("x").bounds  # SECONDS default ladder

    def test_timer_records_even_on_exception(self):
        from tpumr.metrics import Histogram
        h = Histogram("t")
        with pytest.raises(RuntimeError):
            with h.time():
                raise RuntimeError("boom")
        assert h.count == 1

    def test_merge_typed_and_typed_delta(self):
        from tpumr.metrics import Histogram
        from tpumr.metrics.histogram import typed_delta
        a = Histogram("x")
        for v in (0.001, 0.01, 0.1, 1.0):
            a.observe(v)
        snap1 = a.typed()
        a.observe(10.0)
        snap2 = a.typed()
        # delta between cumulative states = just the new observation
        d = typed_delta(snap2, snap1)
        assert d["count"] == 1 and abs(d["sum"] - 10.0) < 1e-9
        assert sum(d["buckets"].values()) == 1
        # unchanged state -> no delta; restart (shrunk count) -> re-base
        assert typed_delta(snap2, snap2) is None
        assert typed_delta(snap1, snap2) == snap1
        # merging two full states doubles everything
        m = Histogram("x")
        m.merge_typed(snap2)
        m.merge_typed(snap2)
        assert m.count == 10 and abs(m.sum - 2 * a.sum) < 1e-9
        assert m.max == 10.0 and m.min == 0.001
        # mismatched ladders are dropped, not corrupted
        other = Histogram("y", [1.0, 2.0]).typed()
        m.merge_typed(other)
        assert m.count == 10

    def test_registry_histogram_get_or_create(self):
        from tpumr.metrics import MetricsRegistry
        reg = MetricsRegistry("s")
        h1 = reg.histogram("lat")
        h2 = reg.histogram("lat")
        assert h1 is h2
        h1.observe(0.5)
        snap = reg.snapshot()["lat"]
        assert snap["count"] == 1
        typed = reg.typed_snapshot()
        assert typed["histograms"]["lat"]["count"] == 1

    def test_exact_percentiles(self):
        from tpumr.metrics import exact_percentiles
        assert exact_percentiles([]) == {}
        p = exact_percentiles(list(range(1, 101)))
        assert p["p50"] == 50 and p["p95"] == 95 and p["p99"] == 99
        assert p["count"] == 100 and p["max"] == 100


class TestPrometheus:
    def _system(self):
        ms = MetricsSystem("jobtracker", period_s=3600)
        reg = ms.new_registry("jobtracker")
        reg.incr("heartbeats", 3)
        reg.set_gauge("slots", lambda: {"cpu": 4, "tpu": 2})
        reg.set_gauge("jobs_running", lambda: 1)
        reg.set_gauge("label", lambda: "text-skipped")
        h = reg.histogram("heartbeat_seconds")
        for v in (0.001, 0.002, 0.02, 1.5):
            h.observe(v)
        return ms

    def test_render_and_validate(self):
        from tpumr.metrics import render_exposition, validate_exposition
        text = render_exposition(self._system().typed_snapshot())
        validate_exposition(text)   # raises on any format violation
        lines = text.splitlines()
        assert "# TYPE tpumr_heartbeats counter" in lines
        assert 'tpumr_heartbeats{source="jobtracker"} 3' in lines
        # composite gauges flatten one level; non-numeric skipped
        assert 'tpumr_slots_cpu{source="jobtracker"} 4' in lines
        assert not any("label" in l for l in lines)
        # cumulative-le histogram series with +Inf == _count
        assert "# TYPE tpumr_heartbeat_seconds histogram" in lines
        inf = [l for l in lines if 'le="+Inf"' in l]
        assert inf and inf[0].endswith(" 4")
        assert 'tpumr_heartbeat_seconds_count{source="jobtracker"} 4' \
            in lines

    def test_name_sanitization_and_label_escaping(self):
        from tpumr.metrics import (MetricsRegistry, render_exposition,
                                   validate_exposition)
        from tpumr.metrics.prometheus import sanitize_name
        assert sanitize_name("rpc.get-map output") == "rpc_get_map_output"
        assert sanitize_name("9lives")[0] == "_"
        ms = MetricsSystem("t", period_s=3600)
        reg = MetricsRegistry('trk "weird"\nname')
        reg.incr("some.metric-name", 1)
        ms.register(reg)
        text = render_exposition(ms.typed_snapshot())
        validate_exposition(text)
        assert "tpumr_some_metric_name" in text

    def test_validator_rejects_bad_expositions(self):
        from tpumr.metrics import validate_exposition
        with pytest.raises(ValueError, match="no # TYPE"):
            validate_exposition("tpumr_x 1\n")
        with pytest.raises(ValueError, match="unparseable"):
            validate_exposition("# TYPE tpumr_x gauge\ntpumr_x one\n")
        with pytest.raises(ValueError, match="not cumulative"):
            validate_exposition(
                "# TYPE h histogram\n"
                'h_bucket{le="1"} 5\nh_bucket{le="+Inf"} 3\n'
                "h_sum 1\nh_count 3\n")
        with pytest.raises(ValueError, match=r"\+Inf"):
            validate_exposition(
                "# TYPE h histogram\n"
                'h_bucket{le="1"} 1\nh_sum 1\nh_count 1\n')
        with pytest.raises(ValueError, match="_count"):
            validate_exposition(
                "# TYPE h histogram\n"
                'h_bucket{le="+Inf"} 2\nh_sum 1\nh_count 3\n')
        with pytest.raises(ValueError, match="duplicate TYPE"):
            validate_exposition(
                "# TYPE g gauge\n# TYPE g gauge\ng 1\n")

    def test_conflicting_kinds_qualified_by_source(self):
        """The same metric name as a counter in one source and a gauge
        in another must not produce two TYPE lines for one family."""
        from tpumr.metrics import render_exposition, validate_exposition
        ms = MetricsSystem("t", period_s=3600)
        ms.new_registry("a").incr("depth", 2)
        ms.new_registry("b").set_gauge("depth", lambda: 5)
        text = render_exposition(ms.typed_snapshot())
        validate_exposition(text)
        assert "tpumr_b_depth" in text


class TestClusterAggregator:
    def _piggyback(self, n_fetches: int, errors: int = 2) -> dict:
        from tpumr.metrics import MetricsRegistry
        reg = MetricsRegistry("shuffle")
        reg.incr("fetch_errors", errors)
        h = reg.histogram("fetch_seconds")
        for _ in range(n_fetches):
            h.observe(0.01)
        t = reg.typed_snapshot()
        return {"shuffle": t,
                "tasktracker": {"counters": {"cpu_maps_launched": 4},
                                "gauges": {"slot_utilization":
                                           {"cpu": 0.5}}}}

    def test_cumulative_merge_is_idempotent(self):
        from tpumr.metrics import MetricsRegistry
        from tpumr.metrics.cluster import ClusterAggregator
        agg = ClusterAggregator(MetricsRegistry("cluster"))
        pb = self._piggyback(10)
        agg.merge("t1", pb)
        agg.merge("t1", pb)       # replayed heartbeat: no double count
        snap = agg.registry.snapshot()
        assert snap["shuffle_fetch_errors"] == 2
        assert snap["shuffle_fetch_seconds"]["count"] == 10
        assert snap["cpu_maps_launched"] == 4
        # a second tracker's state adds
        agg.merge("t2", self._piggyback(5))
        snap = agg.registry.snapshot()
        assert snap["shuffle_fetch_errors"] == 4
        assert snap["shuffle_fetch_seconds"]["count"] == 15
        assert agg.gauge_totals()["slot_utilization_cpu"] == 1.0
        assert set(agg.gauge_rows()) == {"t1", "t2"}

    def test_restart_rebases_instead_of_negative(self):
        from tpumr.metrics import MetricsRegistry
        from tpumr.metrics.cluster import ClusterAggregator
        agg = ClusterAggregator(MetricsRegistry("cluster"))
        agg.merge("t1", self._piggyback(10))
        # tracker restarted: cumulative values shrank — the shrunk
        # state is folded as a fresh baseline, never a negative delta
        agg.merge("t1", self._piggyback(3, errors=1))
        snap = agg.registry.snapshot()
        assert snap["shuffle_fetch_seconds"]["count"] == 13
        assert snap["shuffle_fetch_errors"] == 3
        agg.forget("t1")
        assert agg.gauge_rows() == {}

    def test_malformed_piggyback_is_dropped(self):
        from tpumr.metrics import MetricsRegistry
        from tpumr.metrics.cluster import ClusterAggregator
        agg = ClusterAggregator(MetricsRegistry("cluster"))
        agg.merge("t1", None)
        agg.merge("t1", "garbage")
        agg.merge("t1", {"src": {"histograms": {"h": "not-a-dict"},
                                 "counters": {"c": "NaN-ish"}}})
        assert agg.registry.snapshot() == {}


class TestMetricsSatellites:
    def test_stop_joins_publish_thread(self, tmp_path):
        ms = MetricsSystem("t", period_s=0.05)
        ms.new_registry("s").incr("n")
        path = str(tmp_path / "m.jsonl")
        ms.add_sink(FileSink(path))
        ms.start()
        t = ms._thread
        assert t is not None and t.is_alive()
        ms.stop()
        assert not t.is_alive()          # joined, not orphaned
        assert ms._thread is None
        # final flush happened and the sink's handle was closed
        assert open(path).read().strip()
        assert ms._sinks[0]._f is None

    def test_file_sink_holds_one_handle(self, tmp_path):
        sink = FileSink(str(tmp_path / "m.jsonl"))
        sink.put_metrics({"a": 1})
        f = sink._f
        assert f is not None
        sink.put_metrics({"a": 2})
        assert sink._f is f              # same handle, not reopened
        # flush-per-record: both records readable NOW, pre-close
        lines = open(sink.path).read().splitlines()
        assert len(lines) == 2
        assert [json.loads(l)["seq"] for l in lines] == [1, 2]
        sink.close()
        assert sink._f is None
        sink.put_metrics({"a": 3})       # post-close put reopens
        assert len(open(sink.path).read().splitlines()) == 3
        sink.close()

    def _recv_all(self, sock, expect_lines):
        import socket
        got, grams = [], []
        while len(got) < expect_lines:
            try:
                data = sock.recv(65536)
            except socket.timeout:
                break
            grams.append(data)
            got.extend(data.decode().splitlines())
        return got, grams

    def test_udp_sink_single_over_mtu_line(self):
        """One statsd line longer than MAX_DATAGRAM still goes out (its
        own datagram) — UDP caps at ~64KiB, not at our batching MTU."""
        import socket
        from tpumr.metrics import UdpSink
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.settimeout(5)
        sink = UdpSink("127.0.0.1", recv.getsockname()[1])
        big = "m" * (UdpSink.MAX_DATAGRAM + 100)
        sink.put_metrics({"prefix": "p", "sources": {"s": {big: 1}}})
        got, grams = self._recv_all(recv, 1)
        assert got == [f"p.s.{big}:1|g"]
        assert len(grams) == 1
        recv.close()

    def test_udp_sink_splits_exactly_at_mtu_boundary(self):
        """A batch whose next line would push it past MAX_DATAGRAM
        splits there; one that lands exactly ON the limit does not."""
        import socket
        from tpumr.metrics import UdpSink
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.settimeout(5)
        sink = UdpSink("127.0.0.1", recv.getsockname()[1])
        # two lines + newline == exactly MAX_DATAGRAM -> one datagram
        overhead = len("p.s.:1|g")     # per-line chrome around the name
        l1, l2 = 699, UdpSink.MAX_DATAGRAM - 700  # l1 + 1 + l2 == MAX
        names = ["a" * (l1 - overhead), "b" * (l2 - overhead)]
        metrics = {n: 1 for n in names}
        sink.put_metrics({"prefix": "p", "sources": {"s": metrics}})
        got, grams = self._recv_all(recv, 2)
        assert len(got) == 2
        assert len(grams) == 1
        assert len(grams[0]) == UdpSink.MAX_DATAGRAM
        # one byte more and the batch must split into two datagrams,
        # losing nothing
        names[1] += "b"
        metrics = {n: 1 for n in names}
        sink.put_metrics({"prefix": "p", "sources": {"s": metrics}})
        got, grams = self._recv_all(recv, 2)
        assert len(got) == 2
        assert len(grams) == 2
        assert all(len(g) <= UdpSink.MAX_DATAGRAM for g in grams)
        recv.close()

    def test_sinks_from_conf_malformed_udp_values(self):
        from tpumr.metrics import sinks_from_conf
        for bad in ("monitor01", "monitor01:", ":notaport",
                    "host:port:extra:", "host: ", " : "):
            c = JobConf()
            c.set("tpumr.metrics.udp", bad)
            assert sinks_from_conf(c) == [], bad


class WcMapper:
    def configure(self, conf):
        pass

    def map(self, key, value, output, reporter):
        for w in value.split():
            output.collect(w, 1)

    def close(self):
        pass


class SumReducer:
    def configure(self, conf):
        pass

    def reduce(self, key, values, output, reporter):
        output.collect(key, sum(values))

    def close(self):
        pass


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    hist = str(tmp_path_factory.mktemp("hist"))
    conf = JobConf()
    conf.set("mapred.job.tracker.http.port", 0)   # ephemeral
    conf.set("tpumr.history.dir", hist)
    with MiniMRCluster(num_trackers=1, cpu_slots=2, tpu_slots=0,
                       conf=conf) as c:
        c.history_dir = hist
        yield c


def run_wc(cluster, name):
    from tpumr.mapred.job_client import JobClient
    fs = get_filesystem("mem:///")
    fs.write_bytes(f"/mh/{name}.txt", b"a b a\n" * 30)
    conf = cluster.create_job_conf()
    conf.set_input_paths(f"mem:///mh/{name}.txt")
    conf.set_output_path(f"mem:///mh/{name}-out")
    conf.set_class("mapred.mapper.class", WcMapper)
    conf.set_class("mapred.reducer.class", SumReducer)
    result = JobClient(conf).run_job(conf)
    assert result.successful
    return result


class TestJobTrackerHttp:
    def test_endpoints(self, cluster):
        run_wc(cluster, "one")
        base = cluster.master.http_url
        assert base is not None
        code, body = fetch(base + "/json/cluster")
        assert code == 200
        info = json.loads(body)
        assert info["trackers"] == 1 and info["jobs_total"] >= 1

        code, body = fetch(base + "/json/jobs")
        jobs = json.loads(body)
        assert any(j["state"] == "SUCCEEDED" for j in jobs)

        jid = jobs[0]["job_id"]
        code, body = fetch(base + f"/json/job?id={jid}")
        assert json.loads(body)["job_id"] == jid

        code, body = fetch(base + "/json/metrics")
        metrics = json.loads(body)["jobtracker"]
        assert metrics["heartbeats"] >= 1
        assert metrics["jobs_submitted"] >= 1
        assert metrics["maps_launched_cpu"] >= 1
        assert metrics["jobs_succeeded"] >= 1

        code, body = fetch(base + "/json/trackers")
        assert len(json.loads(body)) == 1

        # the uniform top-level /metrics endpoint (same payload shape on
        # every daemon — one scraper config for the whole cluster)
        code, body = fetch(base + "/metrics")
        assert code == 200
        uniform = json.loads(body)
        assert uniform["jobtracker"]["heartbeats"] >= 1

        code, body = fetch(base + "/")
        assert code == 200 and "<html>" in body

        code, body = fetch(base + "/json/nope")
        assert code == 404 and "endpoints" in body

    def test_conf_endpoint_redacts_secrets(self, cluster):
        """Credential-bearing conf values must never reach the status port
        (≈ ConfServlet sanitization) — leaking tpumr.rpc.secret would
        defeat the RPC HMAC auth entirely."""
        master_conf = cluster.master.conf
        master_conf.set("tpumr.rpc.secret", "hunter2-cluster-secret")
        master_conf.set("some.service.password", "pw-value")
        try:
            code, body = fetch(cluster.master.http_url + "/json/conf")
            assert code == 200
            conf = json.loads(body)
            assert "hunter2-cluster-secret" not in body
            assert "pw-value" not in body
            assert conf["tpumr.rpc.secret"] == "*** redacted ***"
            assert conf["some.service.password"] == "*** redacted ***"
        finally:
            master_conf.unset("tpumr.rpc.secret")
            master_conf.unset("some.service.password")

    def test_history_server(self, cluster):
        run_wc(cluster, "two")
        from tpumr.mapred.history_server import JobHistoryServer
        hs = JobHistoryServer(cluster.history_dir).start()
        try:
            code, body = fetch(hs.url + "/json/history")
            summaries = json.loads(body)
            assert any(s.get("state") == "SUCCEEDED" for s in summaries)
            done = [s for s in summaries if s.get("state")][0]
            code, body = fetch(hs.url + f"/json/job?id={done['job_id']}")
            events = json.loads(body)
            kinds = {e["event"] for e in events}
            assert {"JOB_SUBMITTED", "JOB_FINISHED"} <= kinds
        finally:
            hs.stop()

    def test_history_task_drilldown(self, cluster):
        """Per-task drill-down (≈ jobtasks.jsp + TaskGraphServlet): the
        /json/tasks rows carry timings + placement for every attempt,
        and /jobtasks renders the backend-colored timeline SVG."""
        result = run_wc(cluster, "drill")
        jid = str(result.job_id)
        from tpumr.mapred.history_server import JobHistoryServer
        hs = JobHistoryServer(cluster.history_dir).start()
        try:
            code, body = fetch(hs.url + f"/json/tasks?id={jid}")
            assert code == 200
            tasks = json.loads(body)
            maps = [t for t in tasks if t.get("is_map")]
            assert maps, tasks
            for t in maps:
                assert t["state"] == "FINISHED"
                assert t["start_ts"] is not None
                assert t["runtime"] is not None and t["runtime"] >= 0
                assert t["tracker"]
                assert t["run_on_tpu"] is False     # cpu-only cluster
            assert any(not t.get("is_map") for t in tasks)  # the reduce

            code, body = fetch(hs.url + f"/jobtasks?id={jid}")
            assert code == 200
            assert "<svg" in body and "attempt_" in body
            assert "[cpu]" in body      # per-row backend label, not the
            assert "[reduce]" in body   # static legend
            # the index links each job to its drill-down page
            code, index = fetch(hs.url + "/index")
            assert f"/jobtasks?id={jid}" in index
        finally:
            hs.stop()

    def test_placement_series_in_status_and_history(self, cluster):
        """VERDICT r4 #9: every map assignment appends (t, backend) to the
        job's placement series; the finished job's history carries the
        full timeline so a convergence curve plots from any run."""
        result = run_wc(cluster, "plc")
        jid = str(result.job_id)
        jip = cluster.master.jobs[jid]
        tl = jip.placement_timeline()
        assert tl["seq"] and set(tl["seq"]) <= {"T", "c"}
        assert len(tl["t"]) == len(tl["seq"])
        # status carries the TAIL (RPC-polled payload stays bounded)
        assert jip.status_dict()["placement_seq"] == tl["seq"][-512:]
        # history JOB_FINISHED carries it
        from tpumr.mapred.history_server import (JobHistoryServer,
                                                placement_svg)
        hs = JobHistoryServer(cluster.history_dir).start()
        try:
            code, body = fetch(hs.url + f"/json/job?id={jid}")
            events = json.loads(body)
            fin = [e for e in events if e["event"] == "JOB_FINISHED"][0]
            assert fin["placement"]["seq"] == tl["seq"]
        finally:
            hs.stop()
        svg = placement_svg({"seq": "ccTcTT"})
        assert "<svg" in svg and "polyline" in svg
        assert placement_svg({"seq": ""}) == ""

    def test_history_server_redacts_submission_conf(self, tmp_path):
        """The JOB_SUBMITTED event keeps the full conf on disk (recovery
        needs it) but the history status port must mask credentials."""
        import json as _json
        from tpumr.mapred.history_server import JobHistoryServer
        events = [{"event": "JOB_SUBMITTED", "job_id": "job_x_0001",
                   "job_name": "j", "num_maps": 1, "num_reduces": 0,
                   "conf": {"tpumr.rpc.secret": "leak-me",
                            "mapred.job.name": "j"}, "splits": []},
                  {"event": "JOB_FINISHED", "job_id": "job_x_0001",
                   "state": "SUCCEEDED"}]
        with open(tmp_path / "job_x_0001.jsonl", "w") as f:
            f.write("\n".join(_json.dumps(e) for e in events) + "\n")
        hs = JobHistoryServer(str(tmp_path)).start()
        try:
            code, body = fetch(hs.url + "/json/job?id=job_x_0001")
            assert code == 200 and "leak-me" not in body
            served = json.loads(body)[0]["conf"]
            assert served["tpumr.rpc.secret"] == "*** redacted ***"
            assert served["mapred.job.name"] == "j"
        finally:
            hs.stop()
        # the on-disk file is untouched — recovery still sees the secret
        assert "leak-me" in (tmp_path / "job_x_0001.jsonl").read_text()


class TestClusterMetricsE2E:
    """The metrics-v2 acceptance surface: Prometheus exposition on the
    live master, heartbeat-aggregated cluster series, per-method RPC and
    scheduler instrumentation, the per-job stats rollup + CLI, and
    output-byte identity with publishing on vs off."""

    def _poll_prom(self, base, needles, timeout=10.0):
        deadline = time.time() + timeout
        while True:
            code, body = fetch(base + "/metrics/prom")
            assert code == 200
            if all(n in body for n in needles) or time.time() > deadline:
                return body

    def test_prom_scrape_validates_with_cluster_series(self, cluster):
        from tpumr.metrics import validate_exposition
        run_wc(cluster, "prom")
        base = cluster.master.http_url
        # tracker-aggregated series arrive on the next heartbeat after
        # the job — poll briefly rather than sleeping blind
        body = self._poll_prom(base, [
            'tpumr_cpu_maps_launched{source="cluster"}',
            'tpumr_shuffle_fetch_seconds_count{source="cluster"}'])
        validate_exposition(body)
        # cluster-wide utilization gauges + the master's own heartbeat
        # latency histogram (the acceptance criteria series); the
        # utilization names match the trackers' per-host gauge exactly
        assert 'tpumr_slot_utilization_tpu{source="cluster"}' in body
        assert 'tpumr_slot_utilization_cpu{source="cluster"}' in body
        assert 'tpumr_heartbeat_seconds_bucket{source="jobtracker",le=' \
            in body
        # per-method RPC server latency + wire request sizes on the
        # master's surface — rpc_heartbeat_request_bytes IS the
        # heartbeat payload-size series (frame length, not re-encoded)
        assert 'tpumr_rpc_heartbeat_count{source="rpc"}' in body
        assert 'tpumr_rpc_heartbeat_request_bytes_count{source="rpc"}' \
            in body
        # merged tracker counters carry real values
        m = [l for l in body.splitlines()
             if l.startswith('tpumr_cpu_maps_launched{source="cluster"}')]
        assert m and float(m[0].rsplit(" ", 1)[1]) >= 1
        # CI artifact: the scraped exposition body (tier1.yml uploads it)
        with open("/tmp/tpumr-e2e-metrics-prom.txt", "w") as f:
            f.write(body)
        # the JSON twin still serves, now with histogram summaries
        code, body = fetch(base + "/metrics")
        snap = json.loads(body)
        assert snap["jobtracker"]["heartbeat_seconds"]["count"] >= 1
        assert "cluster" in snap

    def test_rpc_and_scheduler_latency_histograms(self, cluster):
        run_wc(cluster, "rpcstats")
        code, body = fetch(cluster.master.http_url + "/metrics")
        snap = json.loads(body)
        # per-method RPC server latency: the heartbeat method must have
        # been dispatched and timed
        assert snap["rpc"]["rpc_heartbeat"]["count"] >= 1
        assert snap["rpc"]["rpc_heartbeat"]["p99"] >= 0
        # scheduler decision timing + per-backend assignment counters
        assert snap["scheduler"]["assign_seconds"]["count"] >= 1
        assert snap["scheduler"]["assigned_cpu_maps"] >= 1
        assert snap["scheduler"]["assigned_reduces"] >= 1

    def test_cluster_page(self, cluster):
        run_wc(cluster, "clpage")
        code, body = fetch(cluster.master.http_url + "/cluster")
        assert code == 200
        assert "Merged distributions" in body
        assert "slot utilization" in body
        assert "Per-tracker gauges" in body
        # the decomposed master locks are observable per class right on
        # the page (wait vs hold for lock=global|trackers|scheduler)
        assert "Master locks" in body
        for which in ("global", "trackers", "scheduler"):
            assert which in body
        # staleness signal on the per-tracker rows: a wedged tracker's
        # merged gauges persist, so without this column it looked
        # healthy until eviction
        assert "last heartbeat" in body
        assert "s ago" in body

    def test_rollup_written_and_cli_prints_it(self, cluster, capsys):
        result = run_wc(cluster, "rollup")
        jid = str(result.job_id)
        import os
        path = os.path.join(cluster.history_dir, f"metrics-{jid}.json")
        assert os.path.exists(path)
        r = json.load(open(path))
        assert r["state"] == "SUCCEEDED"
        assert r["map_latency"]["count"] >= 1
        for k in ("p50", "p95", "p99"):
            assert r["map_latency"][k] >= 0
        assert r["reduce_latency"]["count"] >= 1
        split = r["task_time_split"]
        assert split["cpu_map_s"] > 0 and split["tpu_map_s"] == 0
        assert split["tpu_fraction_of_map_time"] == 0.0
        assert r["counters"]          # counters rode along
        # CI artifact: the per-job rollup (tier1.yml uploads it)
        import shutil
        shutil.copyfile(path, "/tmp/tpumr-e2e-job-metrics.json")

        # `tpumr job stats <id>` prints percentiles + the task-time
        # split from the on-disk rollup — no live master needed
        from tpumr import cli
        rc = cli.main(["job", "stats", jid, cluster.history_dir])
        out = capsys.readouterr().out
        assert rc == 0
        assert "map latency" in out and "p99=" in out
        assert "task time" in out and "tpu" in out and "cpu" in out
        rc = cli.main(["job", "stats", jid, cluster.history_dir, "-json"])
        assert rc == 0
        assert json.loads(capsys.readouterr().out)["job_id"] == jid
        # unknown job: actionable error, not a traceback
        rc = cli.main(["job", "stats", "job_nope_1", cluster.history_dir])
        assert rc == 1
        assert "no stats rollup" in capsys.readouterr().err

    def test_output_bytes_identical_with_publishing_on_vs_off(
            self, tmp_path_factory):
        """Metrics publishing (file sink + heartbeat piggyback) must be
        pure observation: same input, byte-identical job output."""
        from tpumr.mapred.job_client import JobClient
        outputs = {}
        for mode in ("off", "on"):
            base = tmp_path_factory.mktemp(f"mpub-{mode}")
            conf = JobConf()
            conf.set("tpumr.history.dir", str(base / "hist"))
            if mode == "on":
                conf.set("tpumr.metrics.file", str(base / "metrics.jsonl"))
                conf.set("tpumr.metrics.period.ms", 50)
            with MiniMRCluster(num_trackers=1, cpu_slots=2, tpu_slots=0,
                               conf=conf) as c:
                fs = get_filesystem("mem:///")
                fs.write_bytes(f"/mpub{mode}/in.txt", b"x y x z\n" * 40)
                jc = c.create_job_conf()
                jc.set_input_paths(f"mem:///mpub{mode}/in.txt")
                jc.set_output_path(f"mem:///mpub{mode}/out")
                jc.set_class("mapred.mapper.class", WcMapper)
                jc.set_class("mapred.reducer.class", SumReducer)
                assert JobClient(jc).run_job(jc).successful
                outputs[mode] = b"".join(
                    fs.read_bytes(st.path)
                    for st in sorted(fs.list_status(f"/mpub{mode}/out"),
                                     key=lambda s: str(s.path))
                    if "part-" in str(st.path))
            if mode == "on":
                # the sink actually published something
                assert (base / "metrics.jsonl").exists()
                assert open(base / "metrics.jsonl").read().strip()
        assert outputs["on"] == outputs["off"]


class TestTaskTrackerHttp:
    def test_task_detail_page_surfaces_profile(self, tmp_path_factory):
        """The tracker's /task?attempt= detail page inlines the top of
        the attempt's cProfile report (profile.out used to be stranded
        in the task-local dir) and links the full text + child log."""
        hist = str(tmp_path_factory.mktemp("tt-hist"))
        conf = JobConf()
        conf.set("tpumr.history.dir", hist)
        conf.set("mapred.task.tracker.http.port", 0)
        with MiniMRCluster(num_trackers=1, cpu_slots=2, tpu_slots=0,
                           conf=conf) as c:
            fs = get_filesystem("mem:///")
            fs.write_bytes("/ttp/in.txt", b"p q p\n" * 50)
            jc = c.create_job_conf()
            jc.set_input_paths("mem:///ttp/in.txt")
            jc.set_output_path("mem:///ttp/out")
            jc.set_class("mapred.mapper.class", WcMapper)
            jc.set_class("mapred.reducer.class", SumReducer)
            jc.set_num_reduce_tasks(1)
            jc.set("mapred.task.profile", True)
            jc.set("mapred.task.profile.maps", "0")
            jc.set("mapred.task.profile.reduces", "0")
            from tpumr.mapred.job_client import JobClient
            assert JobClient(jc).run_job(jc).successful

            tracker = c.trackers[0]
            base = tracker._http.url
            code, body = fetch(base + "/metrics")
            assert code == 200 and tracker.name in json.loads(body)
            snap = json.loads(body)
            # per-tracker slot-utilization gauge rides the tracker's own
            # registry (and from there the heartbeat piggyback)
            util = snap[tracker.name]["slot_utilization"]
            assert set(util) == {"cpu", "tpu", "reduce"}
            # every daemon serves validated Prometheus exposition
            from tpumr.metrics import validate_exposition
            code, prom = fetch(base + "/metrics/prom")
            assert code == 200
            validate_exposition(prom)
            assert "tpumr_slot_utilization_cpu" in prom
            # the tracker's RPC surface (shuffle serving) was timed
            assert 'tpumr_rpc_get_map_output_chunk_count{source="rpc"}' \
                in prom
            profiled = tracker.list_profiles()
            assert profiled
            aid = profiled[0]
            code, body = fetch(base + f"/task?attempt={aid}")
            assert code == 200
            assert "Profile (top of pstats report)" in body
            assert "ncalls" in body or "function calls" in body
            assert f"/json/profile?attempt={aid}" in body
            # index links each attempt to its detail page
            code, body = fetch(base + "/")
            assert code == 200 and f"/task?attempt={aid}" in body
            # unprofiled attempt renders a hint, not a 500
            code, body = fetch(base + "/task?attempt="
                               "attempt_0_0000_m_000099_0")
            assert code == 200 and "no profile" in body

    def test_profile_top_lines(self):
        from tpumr.mapred.profiler import profile_top_lines
        text = ("# profile of a\n   12 function calls in 0.001s\n\n"
                "   ncalls  tottime  percall\n" +
                "\n".join(f"   row{i}" for i in range(50)))
        top = profile_top_lines(text, n=10)
        assert top[3].lstrip().startswith("ncalls")
        assert len(top) == 14          # header block + 10 rows
        assert profile_top_lines("no header\njust text", n=1) == \
            ["no header"]


class TestNameNodeHttp:
    def test_dfs_endpoints(self, tmp_path):
        from tpumr.dfs.mini_cluster import MiniDFSCluster
        conf = JobConf()
        conf.set("tdfs.http.port", 0)
        with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
            client = c.client()
            with client.create("/h.txt") as f:
                f.write(b"hello")
            base = c.namenode.http_url
            assert base is not None
            code, body = fetch(base + "/json/namenode")
            info = json.loads(body)
            assert info["files"] == 1 and info["datanodes"] == 2
            code, body = fetch(base + "/json/datanodes")
            assert len(json.loads(body)) == 2
            # uniform /metrics on the dfs tier too
            code, body = fetch(base + "/metrics")
            assert code == 200
            ns = json.loads(body)["namenode"]["namespace"]
            assert ns["files"] == 1 and ns["datanodes"] == 2


class TestHtmlDashboard:
    """HTML views ≈ webapps/{job,hdfs,history} JSP dashboards (VERDICT r1
    missing #8): jobs table with backend placement, task drill-down,
    tracker and datanode tables."""

    def test_jobtracker_index_and_job_drilldown(self, cluster):
        run_wc(cluster, "dash")
        base = cluster.master.http_url
        code, body = fetch(base + "/")
        assert code == 200
        assert "<h2>Jobs</h2>" in body and "<table>" in body
        assert "SUCCEEDED" in body
        # jobs table links to the per-job page
        jid = json.loads(fetch(base + "/json/jobs")[1])[0]["job_id"]
        assert f"/job?id={jid}" in body

        code, body = fetch(base + f"/job?id={jid}")
        assert code == 200
        assert "map tasks" in body
        # backend placement column: cpu-only cluster -> 'cpu' cells
        assert "<td>cpu</td>" in body
        assert "Counters" in body

        code, body = fetch(base + "/trackers")
        assert code == 200
        assert "tracker_0" in body and "cpu slots" in body

        # raw json dump still reachable
        code, body = fetch(base + "/raw")
        assert code == 200 and "/json/cluster" in body

    def test_job_page_missing_id_is_not_500(self, cluster):
        base = cluster.master.http_url
        code, body = fetch(base + "/job")
        assert code == 200
        assert "missing parameter" in body or "error" in body

    def test_namenode_index_page(self, tmp_path):
        from tpumr.dfs.mini_cluster import MiniDFSCluster
        conf = JobConf()
        conf.set("dfs.replication", 1)
        conf.set("tdfs.http.port", 0)
        with MiniDFSCluster(num_datanodes=1, conf=conf) as c:
            client = c.client()
            with client.create("/dash/f") as f:
                f.write(b"x" * 100)
            url = c.namenode.http_url
            assert url is not None
            code, body = fetch(url + "/")
            assert code == 200
            assert "NameNode" in body and "DataNodes" in body
            assert "HEALTHY" in body

    def test_history_index_page(self, cluster):
        run_wc(cluster, "hist-dash")
        from tpumr.mapred.history_server import JobHistoryServer
        hs = JobHistoryServer(cluster.history_dir).start()
        try:
            code, body = fetch(hs.url + "/")
            assert code == 200
            assert "Job History" in body and "SUCCEEDED" in body
        finally:
            hs.stop()


class TestDashboardEscaping:
    def test_malicious_job_name_and_counter_escaped(self, cluster):
        """User-controlled strings (job name, counter group/name) must
        never reach dashboard HTML unescaped (stored XSS)."""
        from tpumr.mapred.job_client import JobClient

        payload = "<img src=x onerror=alert(1)>"
        fs = get_filesystem("mem:///")
        fs.write_bytes("/xss/in.txt", b"a b\n" * 5)
        conf = cluster.create_job_conf()
        conf.set_job_name(payload)
        conf.set_input_paths("mem:///xss/in.txt")
        conf.set_output_path("mem:///xss/out")
        conf.set_class("mapred.mapper.class", XssCounterMapper)
        assert JobClient(conf).run_job(conf).successful

        base = cluster.master.http_url
        jid = [j["job_id"] for j in
               json.loads(fetch(base + "/json/jobs")[1])][-1]
        _, body = fetch(base + f"/job?id={jid}")
        assert payload not in body  # raw markup never emitted
        assert "&lt;img" in body or "&lt;script" in body

        from tpumr.mapred.history_server import JobHistoryServer
        hs = JobHistoryServer(cluster.history_dir).start()
        try:
            _, hbody = fetch(hs.url + "/")
            assert payload not in hbody
        finally:
            hs.stop()


class XssCounterMapper:
    def configure(self, conf):
        pass

    def map(self, key, value, output, reporter):
        reporter.incr_counter("g", "<script>alert(2)</script>")
        output.collect(value, 1)

    def close(self):
        pass
