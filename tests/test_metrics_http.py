"""Metrics system + HTTP status tier + history server ≈ metrics2,
HttpServer/webapps, JobHistoryServer (SURVEY.md §5)."""

import json
import time
import urllib.request

import pytest

from tpumr.fs import get_filesystem
from tpumr.metrics import FileSink, MetricsRegistry, MetricsSystem
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.mini_cluster import MiniMRCluster


def fetch(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class TestMetricsCore:
    def test_registry_counters_and_gauges(self):
        reg = MetricsRegistry("x")
        reg.incr("events")
        reg.incr("events", 4)
        reg.set_gauge("depth", lambda: 7)
        reg.set_gauge("static", 3)
        snap = reg.snapshot()
        assert snap == {"events": 5, "depth": 7, "static": 3}

    def test_broken_gauge_survives(self):
        reg = MetricsRegistry("x")
        reg.set_gauge("bad", lambda: 1 / 0)
        assert "error" in str(reg.snapshot()["bad"])

    def test_system_publish_to_file_sink(self, tmp_path):
        ms = MetricsSystem("test", period_s=3600)
        reg = ms.new_registry("src1")
        reg.incr("n", 2)
        path = str(tmp_path / "metrics.jsonl")
        ms.add_sink(FileSink(path))
        ms.publish_once()
        rec = json.loads(open(path).read().splitlines()[0])
        assert rec["prefix"] == "test"
        assert rec["sources"]["src1"]["n"] == 2

    def test_file_sink_stamps_host_and_sequence(self, tmp_path):
        """FileSink records carry hostname + a monotonic per-sink seq so
        interleaved daemon logs (per-host files concatenated later) can
        be totally ordered — wall-clock ts alone cannot do that across
        hosts or clock steps; a seq gap is a dropped-record tell."""
        import socket
        ms = MetricsSystem("test", period_s=3600)
        ms.new_registry("src").incr("n")
        path = str(tmp_path / "m.jsonl")
        ms.add_sink(FileSink(path))
        ms.publish_once()
        ms.publish_once()
        ms.publish_once()
        recs = [json.loads(line) for line in open(path)]
        assert [r["seq"] for r in recs] == [1, 2, 3]
        assert all(r["host"] == socket.gethostname() for r in recs)


    def test_udp_sink_statsd_lines_and_conf_wiring(self, tmp_path):
        """UdpSink (the GangliaSink role): statsd gauge lines over UDP,
        numeric metrics only, MTU-bounded batching; sinks_from_conf wires
        both sink kinds from daemon conf."""
        import socket

        from tpumr.metrics import UdpSink, sinks_from_conf
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.settimeout(5)
        port = recv.getsockname()[1]

        ms = MetricsSystem("td", period_s=3600)
        reg = ms.new_registry("jt")
        reg.incr("heartbeats", 7)
        reg.set_gauge("ratio", lambda: 0.5)
        reg.set_gauge("label", lambda: "text-is-skipped")
        ms.add_sink(UdpSink("127.0.0.1", port))
        ms.publish_once()
        lines = recv.recv(65536).decode().splitlines()
        assert "td.jt.heartbeats:7|g" in lines
        assert "td.jt.ratio:0.5|g" in lines
        assert not any("label" in l for l in lines)

        # many metrics split across MTU-sized datagrams, none lost
        reg2 = ms.new_registry("big")
        for i in range(200):
            reg2.incr(f"metric_{i:03d}", i)
        ms.publish_once()
        got = []
        while len(got) < 202:
            try:
                got.extend(recv.recv(65536).decode().splitlines())
            except socket.timeout:
                break
        assert len([l for l in got if l.startswith("td.big.")]) == 200
        recv.close()

        from tpumr.mapred.jobconf import JobConf
        conf = JobConf()
        conf.set("tpumr.metrics.file", str(tmp_path / "m.jsonl"))
        conf.set("tpumr.metrics.udp", f"127.0.0.1:{port}")
        kinds = {type(s).__name__ for s in sinks_from_conf(conf)}
        assert kinds == {"FileSink", "UdpSink"}
        assert sinks_from_conf(JobConf()) == []

        # a typo'd observability knob must not kill the daemon
        for bad in ("monitor01", "monitor01:", ":notaport"):
            c = JobConf()
            c.set("tpumr.metrics.udp", bad)
            assert sinks_from_conf(c) == []


class WcMapper:
    def configure(self, conf):
        pass

    def map(self, key, value, output, reporter):
        for w in value.split():
            output.collect(w, 1)

    def close(self):
        pass


class SumReducer:
    def configure(self, conf):
        pass

    def reduce(self, key, values, output, reporter):
        output.collect(key, sum(values))

    def close(self):
        pass


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    hist = str(tmp_path_factory.mktemp("hist"))
    conf = JobConf()
    conf.set("mapred.job.tracker.http.port", 0)   # ephemeral
    conf.set("tpumr.history.dir", hist)
    with MiniMRCluster(num_trackers=1, cpu_slots=2, tpu_slots=0,
                       conf=conf) as c:
        c.history_dir = hist
        yield c


def run_wc(cluster, name):
    from tpumr.mapred.job_client import JobClient
    fs = get_filesystem("mem:///")
    fs.write_bytes(f"/mh/{name}.txt", b"a b a\n" * 30)
    conf = cluster.create_job_conf()
    conf.set_input_paths(f"mem:///mh/{name}.txt")
    conf.set_output_path(f"mem:///mh/{name}-out")
    conf.set_class("mapred.mapper.class", WcMapper)
    conf.set_class("mapred.reducer.class", SumReducer)
    result = JobClient(conf).run_job(conf)
    assert result.successful
    return result


class TestJobTrackerHttp:
    def test_endpoints(self, cluster):
        run_wc(cluster, "one")
        base = cluster.master.http_url
        assert base is not None
        code, body = fetch(base + "/json/cluster")
        assert code == 200
        info = json.loads(body)
        assert info["trackers"] == 1 and info["jobs_total"] >= 1

        code, body = fetch(base + "/json/jobs")
        jobs = json.loads(body)
        assert any(j["state"] == "SUCCEEDED" for j in jobs)

        jid = jobs[0]["job_id"]
        code, body = fetch(base + f"/json/job?id={jid}")
        assert json.loads(body)["job_id"] == jid

        code, body = fetch(base + "/json/metrics")
        metrics = json.loads(body)["jobtracker"]
        assert metrics["heartbeats"] >= 1
        assert metrics["jobs_submitted"] >= 1
        assert metrics["maps_launched_cpu"] >= 1
        assert metrics["jobs_succeeded"] >= 1

        code, body = fetch(base + "/json/trackers")
        assert len(json.loads(body)) == 1

        # the uniform top-level /metrics endpoint (same payload shape on
        # every daemon — one scraper config for the whole cluster)
        code, body = fetch(base + "/metrics")
        assert code == 200
        uniform = json.loads(body)
        assert uniform["jobtracker"]["heartbeats"] >= 1

        code, body = fetch(base + "/")
        assert code == 200 and "<html>" in body

        code, body = fetch(base + "/json/nope")
        assert code == 404 and "endpoints" in body

    def test_conf_endpoint_redacts_secrets(self, cluster):
        """Credential-bearing conf values must never reach the status port
        (≈ ConfServlet sanitization) — leaking tpumr.rpc.secret would
        defeat the RPC HMAC auth entirely."""
        master_conf = cluster.master.conf
        master_conf.set("tpumr.rpc.secret", "hunter2-cluster-secret")
        master_conf.set("some.service.password", "pw-value")
        try:
            code, body = fetch(cluster.master.http_url + "/json/conf")
            assert code == 200
            conf = json.loads(body)
            assert "hunter2-cluster-secret" not in body
            assert "pw-value" not in body
            assert conf["tpumr.rpc.secret"] == "*** redacted ***"
            assert conf["some.service.password"] == "*** redacted ***"
        finally:
            master_conf.unset("tpumr.rpc.secret")
            master_conf.unset("some.service.password")

    def test_history_server(self, cluster):
        run_wc(cluster, "two")
        from tpumr.mapred.history_server import JobHistoryServer
        hs = JobHistoryServer(cluster.history_dir).start()
        try:
            code, body = fetch(hs.url + "/json/history")
            summaries = json.loads(body)
            assert any(s.get("state") == "SUCCEEDED" for s in summaries)
            done = [s for s in summaries if s.get("state")][0]
            code, body = fetch(hs.url + f"/json/job?id={done['job_id']}")
            events = json.loads(body)
            kinds = {e["event"] for e in events}
            assert {"JOB_SUBMITTED", "JOB_FINISHED"} <= kinds
        finally:
            hs.stop()

    def test_history_task_drilldown(self, cluster):
        """Per-task drill-down (≈ jobtasks.jsp + TaskGraphServlet): the
        /json/tasks rows carry timings + placement for every attempt,
        and /jobtasks renders the backend-colored timeline SVG."""
        result = run_wc(cluster, "drill")
        jid = str(result.job_id)
        from tpumr.mapred.history_server import JobHistoryServer
        hs = JobHistoryServer(cluster.history_dir).start()
        try:
            code, body = fetch(hs.url + f"/json/tasks?id={jid}")
            assert code == 200
            tasks = json.loads(body)
            maps = [t for t in tasks if t.get("is_map")]
            assert maps, tasks
            for t in maps:
                assert t["state"] == "FINISHED"
                assert t["start_ts"] is not None
                assert t["runtime"] is not None and t["runtime"] >= 0
                assert t["tracker"]
                assert t["run_on_tpu"] is False     # cpu-only cluster
            assert any(not t.get("is_map") for t in tasks)  # the reduce

            code, body = fetch(hs.url + f"/jobtasks?id={jid}")
            assert code == 200
            assert "<svg" in body and "attempt_" in body
            assert "[cpu]" in body      # per-row backend label, not the
            assert "[reduce]" in body   # static legend
            # the index links each job to its drill-down page
            code, index = fetch(hs.url + "/index")
            assert f"/jobtasks?id={jid}" in index
        finally:
            hs.stop()

    def test_placement_series_in_status_and_history(self, cluster):
        """VERDICT r4 #9: every map assignment appends (t, backend) to the
        job's placement series; the finished job's history carries the
        full timeline so a convergence curve plots from any run."""
        result = run_wc(cluster, "plc")
        jid = str(result.job_id)
        jip = cluster.master.jobs[jid]
        tl = jip.placement_timeline()
        assert tl["seq"] and set(tl["seq"]) <= {"T", "c"}
        assert len(tl["t"]) == len(tl["seq"])
        # status carries the TAIL (RPC-polled payload stays bounded)
        assert jip.status_dict()["placement_seq"] == tl["seq"][-512:]
        # history JOB_FINISHED carries it
        from tpumr.mapred.history_server import (JobHistoryServer,
                                                placement_svg)
        hs = JobHistoryServer(cluster.history_dir).start()
        try:
            code, body = fetch(hs.url + f"/json/job?id={jid}")
            events = json.loads(body)
            fin = [e for e in events if e["event"] == "JOB_FINISHED"][0]
            assert fin["placement"]["seq"] == tl["seq"]
        finally:
            hs.stop()
        svg = placement_svg({"seq": "ccTcTT"})
        assert "<svg" in svg and "polyline" in svg
        assert placement_svg({"seq": ""}) == ""

    def test_history_server_redacts_submission_conf(self, tmp_path):
        """The JOB_SUBMITTED event keeps the full conf on disk (recovery
        needs it) but the history status port must mask credentials."""
        import json as _json
        from tpumr.mapred.history_server import JobHistoryServer
        events = [{"event": "JOB_SUBMITTED", "job_id": "job_x_0001",
                   "job_name": "j", "num_maps": 1, "num_reduces": 0,
                   "conf": {"tpumr.rpc.secret": "leak-me",
                            "mapred.job.name": "j"}, "splits": []},
                  {"event": "JOB_FINISHED", "job_id": "job_x_0001",
                   "state": "SUCCEEDED"}]
        with open(tmp_path / "job_x_0001.jsonl", "w") as f:
            f.write("\n".join(_json.dumps(e) for e in events) + "\n")
        hs = JobHistoryServer(str(tmp_path)).start()
        try:
            code, body = fetch(hs.url + "/json/job?id=job_x_0001")
            assert code == 200 and "leak-me" not in body
            served = json.loads(body)[0]["conf"]
            assert served["tpumr.rpc.secret"] == "*** redacted ***"
            assert served["mapred.job.name"] == "j"
        finally:
            hs.stop()
        # the on-disk file is untouched — recovery still sees the secret
        assert "leak-me" in (tmp_path / "job_x_0001.jsonl").read_text()


class TestTaskTrackerHttp:
    def test_task_detail_page_surfaces_profile(self, tmp_path_factory):
        """The tracker's /task?attempt= detail page inlines the top of
        the attempt's cProfile report (profile.out used to be stranded
        in the task-local dir) and links the full text + child log."""
        hist = str(tmp_path_factory.mktemp("tt-hist"))
        conf = JobConf()
        conf.set("tpumr.history.dir", hist)
        conf.set("mapred.task.tracker.http.port", 0)
        with MiniMRCluster(num_trackers=1, cpu_slots=2, tpu_slots=0,
                           conf=conf) as c:
            fs = get_filesystem("mem:///")
            fs.write_bytes("/ttp/in.txt", b"p q p\n" * 50)
            jc = c.create_job_conf()
            jc.set_input_paths("mem:///ttp/in.txt")
            jc.set_output_path("mem:///ttp/out")
            jc.set_class("mapred.mapper.class", WcMapper)
            jc.set_class("mapred.reducer.class", SumReducer)
            jc.set_num_reduce_tasks(1)
            jc.set("mapred.task.profile", True)
            jc.set("mapred.task.profile.maps", "0")
            jc.set("mapred.task.profile.reduces", "0")
            from tpumr.mapred.job_client import JobClient
            assert JobClient(jc).run_job(jc).successful

            tracker = c.trackers[0]
            base = tracker._http.url
            code, body = fetch(base + "/metrics")
            assert code == 200 and tracker.name in json.loads(body)
            profiled = tracker.list_profiles()
            assert profiled
            aid = profiled[0]
            code, body = fetch(base + f"/task?attempt={aid}")
            assert code == 200
            assert "Profile (top of pstats report)" in body
            assert "ncalls" in body or "function calls" in body
            assert f"/json/profile?attempt={aid}" in body
            # index links each attempt to its detail page
            code, body = fetch(base + "/")
            assert code == 200 and f"/task?attempt={aid}" in body
            # unprofiled attempt renders a hint, not a 500
            code, body = fetch(base + "/task?attempt="
                               "attempt_0_0000_m_000099_0")
            assert code == 200 and "no profile" in body

    def test_profile_top_lines(self):
        from tpumr.mapred.profiler import profile_top_lines
        text = ("# profile of a\n   12 function calls in 0.001s\n\n"
                "   ncalls  tottime  percall\n" +
                "\n".join(f"   row{i}" for i in range(50)))
        top = profile_top_lines(text, n=10)
        assert top[3].lstrip().startswith("ncalls")
        assert len(top) == 14          # header block + 10 rows
        assert profile_top_lines("no header\njust text", n=1) == \
            ["no header"]


class TestNameNodeHttp:
    def test_dfs_endpoints(self, tmp_path):
        from tpumr.dfs.mini_cluster import MiniDFSCluster
        conf = JobConf()
        conf.set("tdfs.http.port", 0)
        with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
            client = c.client()
            with client.create("/h.txt") as f:
                f.write(b"hello")
            base = c.namenode.http_url
            assert base is not None
            code, body = fetch(base + "/json/namenode")
            info = json.loads(body)
            assert info["files"] == 1 and info["datanodes"] == 2
            code, body = fetch(base + "/json/datanodes")
            assert len(json.loads(body)) == 2
            # uniform /metrics on the dfs tier too
            code, body = fetch(base + "/metrics")
            assert code == 200
            ns = json.loads(body)["namenode"]["namespace"]
            assert ns["files"] == 1 and ns["datanodes"] == 2


class TestHtmlDashboard:
    """HTML views ≈ webapps/{job,hdfs,history} JSP dashboards (VERDICT r1
    missing #8): jobs table with backend placement, task drill-down,
    tracker and datanode tables."""

    def test_jobtracker_index_and_job_drilldown(self, cluster):
        run_wc(cluster, "dash")
        base = cluster.master.http_url
        code, body = fetch(base + "/")
        assert code == 200
        assert "<h2>Jobs</h2>" in body and "<table>" in body
        assert "SUCCEEDED" in body
        # jobs table links to the per-job page
        jid = json.loads(fetch(base + "/json/jobs")[1])[0]["job_id"]
        assert f"/job?id={jid}" in body

        code, body = fetch(base + f"/job?id={jid}")
        assert code == 200
        assert "map tasks" in body
        # backend placement column: cpu-only cluster -> 'cpu' cells
        assert "<td>cpu</td>" in body
        assert "Counters" in body

        code, body = fetch(base + "/trackers")
        assert code == 200
        assert "tracker_0" in body and "cpu slots" in body

        # raw json dump still reachable
        code, body = fetch(base + "/raw")
        assert code == 200 and "/json/cluster" in body

    def test_job_page_missing_id_is_not_500(self, cluster):
        base = cluster.master.http_url
        code, body = fetch(base + "/job")
        assert code == 200
        assert "missing parameter" in body or "error" in body

    def test_namenode_index_page(self, tmp_path):
        from tpumr.dfs.mini_cluster import MiniDFSCluster
        conf = JobConf()
        conf.set("dfs.replication", 1)
        conf.set("tdfs.http.port", 0)
        with MiniDFSCluster(num_datanodes=1, conf=conf) as c:
            client = c.client()
            with client.create("/dash/f") as f:
                f.write(b"x" * 100)
            url = c.namenode.http_url
            assert url is not None
            code, body = fetch(url + "/")
            assert code == 200
            assert "NameNode" in body and "DataNodes" in body
            assert "HEALTHY" in body

    def test_history_index_page(self, cluster):
        run_wc(cluster, "hist-dash")
        from tpumr.mapred.history_server import JobHistoryServer
        hs = JobHistoryServer(cluster.history_dir).start()
        try:
            code, body = fetch(hs.url + "/")
            assert code == 200
            assert "Job History" in body and "SUCCEEDED" in body
        finally:
            hs.stop()


class TestDashboardEscaping:
    def test_malicious_job_name_and_counter_escaped(self, cluster):
        """User-controlled strings (job name, counter group/name) must
        never reach dashboard HTML unescaped (stored XSS)."""
        from tpumr.mapred.job_client import JobClient

        payload = "<img src=x onerror=alert(1)>"
        fs = get_filesystem("mem:///")
        fs.write_bytes("/xss/in.txt", b"a b\n" * 5)
        conf = cluster.create_job_conf()
        conf.set_job_name(payload)
        conf.set_input_paths("mem:///xss/in.txt")
        conf.set_output_path("mem:///xss/out")
        conf.set_class("mapred.mapper.class", XssCounterMapper)
        assert JobClient(conf).run_job(conf).successful

        base = cluster.master.http_url
        jid = [j["job_id"] for j in
               json.loads(fetch(base + "/json/jobs")[1])][-1]
        _, body = fetch(base + f"/job?id={jid}")
        assert payload not in body  # raw markup never emitted
        assert "&lt;img" in body or "&lt;script" in body

        from tpumr.mapred.history_server import JobHistoryServer
        hs = JobHistoryServer(cluster.history_dir).start()
        try:
            _, hbody = fetch(hs.url + "/")
            assert payload not in hbody
        finally:
            hs.stop()


class XssCounterMapper:
    def configure(self, conf):
        pass

    def map(self, key, value, output, reporter):
        reporter.incr_counter("g", "<script>alert(2)</script>")
        output.collect(value, 1)

    def close(self):
        pass
