"""The new-API helper library (≈ mapreduce/lib/): mappers, reducers,
partitioners, lazy output, and JobControl — driven end-to-end through the
new-API Job facade (reference: src/mapred/org/apache/hadoop/mapreduce/lib/)."""

import pytest

from tpumr.fs import FileSystem, get_filesystem
from tpumr.mapred.jobconf import JobConf
from tpumr.mapreduce import Job, Mapper
from tpumr.mapreduce.lib import (BinaryPartitioner, ControlledJob,
                                 InverseMapper, IntSumReducer, JobControl,
                                 KeyFieldBasedPartitioner, LazyOutputFormat,
                                 LongSumReducer, MultithreadedMapper,
                                 RegexMapper, TokenCounterMapper)


@pytest.fixture(autouse=True)
def _clear_fs():
    yield
    FileSystem.clear_cache()


def read_parts(fs, outdir: str) -> str:
    out = []
    for st in sorted(fs.list_status(outdir), key=lambda s: str(s.path)):
        if "part-" in str(st.path):
            out.append(fs.read_bytes(st.path).decode())
    return "".join(out)


def new_job(name: str, inp: str, out: str) -> Job:
    job = Job(JobConf(), name=name)
    job.add_input_path(inp)
    job.set_output_path(out)
    return job


class TestLibEndToEnd:
    def test_wordcount_through_new_api(self):
        """The canonical example, all-new-API: TokenCounterMapper +
        IntSumReducer (≈ the reference's rewritten WordCount.java)."""
        fs = get_filesystem("mem:///")
        fs.write_bytes("/nl/in.txt", b"ab cd ab\nef ab cd\n")
        job = new_job("wc-new", "mem:///nl/in.txt", "mem:///nl/out")
        job.set_mapper_class(TokenCounterMapper)
        job.set_combiner_class(IntSumReducer)
        job.set_reducer_class(IntSumReducer)
        job.set_num_reduce_tasks(1)
        assert job.wait_for_completion()
        text = read_parts(fs, "/nl/out")
        assert "ab\t3" in text and "cd\t2" in text and "ef\t1" in text

    def test_grep_through_new_api(self):
        """RegexMapper + LongSumReducer ≈ the new-API Grep example."""
        fs = get_filesystem("mem:///")
        fs.write_bytes("/nl/g.txt", b"error: one\nok\nerror: two\n")
        job = new_job("grep-new", "mem:///nl/g.txt", "mem:///nl/gout")
        job.conf.set("mapreduce.mapper.regex", r"error: (\w+)")
        job.conf.set("mapreduce.mapper.regex.group", 1)
        job.set_mapper_class(RegexMapper)
        job.set_reducer_class(LongSumReducer)
        assert job.wait_for_completion()
        text = read_parts(fs, "/nl/gout")
        assert "one\t1" in text and "two\t1" in text and "ok" not in text

    def test_inverse_mapper(self):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/nl/i.txt", b"x\ny\n")
        job = new_job("inv", "mem:///nl/i.txt", "mem:///nl/iout")
        job.set_mapper_class(InverseMapper)
        job.set_num_reduce_tasks(0)
        assert job.wait_for_completion()
        # TextInputFormat keys are byte offsets; inverted => value is offset
        text = read_parts(fs, "/nl/iout")
        assert text.splitlines()[0].startswith("x\t")

    def test_multithreaded_mapper(self):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/nl/mt.txt", b"".join(b"w%d\n" % i
                                              for i in range(200)))
        job = new_job("mt", "mem:///nl/mt.txt", "mem:///nl/mtout")
        job.conf.set_class("mapreduce.mapper.multithreadedmapper.class",
                           TokenCounterMapper)
        job.conf.set("mapreduce.mapper.multithreadedmapper.threads", 4)
        job.set_mapper_class(MultithreadedMapper)
        job.set_reducer_class(IntSumReducer)
        assert job.wait_for_completion()
        text = read_parts(fs, "/nl/mtout")
        assert len(text.splitlines()) == 200
        assert "w0\t1" in text

    def test_multithreaded_mapper_propagates_error(self):
        class Boom(Mapper):
            def map(self, key, value, context):
                raise ValueError("inner mapper failure")

        fs = get_filesystem("mem:///")
        fs.write_bytes("/nl/mte.txt", b"a\nb\n")
        job = new_job("mte", "mem:///nl/mte.txt", "mem:///nl/mteout")
        job.conf.set_class("mapreduce.mapper.multithreadedmapper.class",
                           Boom)
        job.set_mapper_class(MultithreadedMapper)
        assert not job.wait_for_completion()
        assert "inner mapper failure" in job.error


class TestPartitioners:
    def test_binary_partitioner_ranges(self):
        import zlib
        p = BinaryPartitioner()                 # whole key
        q = BinaryPartitioner(left=0, right=1)  # first two bytes
        assert p.get_partition(b"aa-111", None, 16) == \
            zlib.crc32(b"aa-111") % 16
        assert q.get_partition(b"aa-111", None, 16) == \
            zlib.crc32(b"aa") % 16
        assert q.get_partition(b"aa-111", None, 16) == \
            q.get_partition(b"aa-222", None, 16)   # same 2-byte prefix

    def test_key_field_partitioner_delegates(self):
        p = KeyFieldBasedPartitioner(num_fields=1)
        assert p.get_partition("k1\tx", None, 8) == \
            p.get_partition("k1\ty", None, 8)

    def test_partitioner_wired_through_job(self):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/nl/p.txt", b"a 1\na 2\nb 3\n")

        job = new_job("part", "mem:///nl/p.txt", "mem:///nl/pout")
        job.set_mapper_class(TokenCounterMapper)
        job.set_reducer_class(IntSumReducer)
        job.set_partitioner_class(BinaryPartitioner)
        job.set_num_reduce_tasks(2)
        assert job.wait_for_completion()
        text = read_parts(fs, "/nl/pout")
        assert "a\t2" in text and "b\t1" in text


class TestLazyOutput:
    def test_empty_partition_writes_no_part_file(self):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/nl/lz.txt", b"only one key\n")
        job = new_job("lazy", "mem:///nl/lz.txt", "mem:///nl/lzout")
        from tpumr.mapreduce.lib import TextOutputFormat
        job.set_mapper_class(TokenCounterMapper)
        job.set_reducer_class(IntSumReducer)
        LazyOutputFormat.set_output_format_class(job, TextOutputFormat)
        job.set_num_reduce_tasks(4)             # 3 keys -> >=1 empty part
        assert job.wait_for_completion()
        parts = [st for st in fs.list_status("/nl/lzout")
                 if "part-" in str(st.path)]
        assert 0 < len(parts) < 4               # empty partitions: no file
        text = read_parts(fs, "/nl/lzout")
        assert "only\t1" in text and "one\t1" in text and "key\t1" in text


class TestJobControl:
    def _mk(self, fs, name, inp, out):
        job = new_job(name, inp, out)
        job.set_mapper_class(TokenCounterMapper)
        job.set_reducer_class(IntSumReducer)
        return job

    def test_dependency_order_and_success(self):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/jc/in.txt", b"x y x\n")
        j1 = self._mk(fs, "first", "mem:///jc/in.txt", "mem:///jc/out1")
        # second consumes the first's output
        j2 = self._mk(fs, "second", "mem:///jc/out1", "mem:///jc/out2")
        jc = JobControl()
        c1 = jc.add_job(ControlledJob(j1))
        c2 = jc.add_job(ControlledJob(j2, depending=[c1]))
        jc.run()
        assert jc.all_finished and not jc.failed_jobs()
        assert c1.state == ControlledJob.SUCCESS
        assert c2.state == ControlledJob.SUCCESS
        assert "x\t1" in read_parts(fs, "/jc/out2")  # counted the counts

    def test_dependent_failure_propagates(self):
        fs = get_filesystem("mem:///")
        j1 = self._mk(fs, "bad", "mem:///jc/missing", "mem:///jc/bout1")
        j2 = self._mk(fs, "after", "mem:///jc/bout1", "mem:///jc/bout2")
        jc = JobControl()
        c1 = jc.add_job(ControlledJob(j1))
        c2 = jc.add_job(ControlledJob(j2, depending=[c1]))
        jc.run()
        assert c1.state == ControlledJob.FAILED
        assert c2.state == ControlledJob.DEPENDENT_FAILED
        assert jc.failed_jobs() == [c1, c2]
