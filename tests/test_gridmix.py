"""Gridmix-lite harness ≈ src/benchmarks/gridmix (SURVEY.md §2.4)."""

import json

from tpumr.benchmarks.gridmix import run
from tpumr.cli import main as cli_main


def test_small_mix_succeeds():
    report = run("small", root="mem:///gmx", cpu_only=True)
    assert report["succeeded"], report
    assert set(report["jobs"]) == {"wordcount", "grep", "randomwriter",
                                   "sort", "kmeans", "pi"}
    assert all(j["ok"] for j in report["jobs"].values())
    assert report["total_wall_s"] > 0


def test_cli_entry(capsys):
    assert cli_main(["gridmix", "--scale", "small",
                     "--root", "mem:///gmx2", "--cpu-only"]) == 0
    out = capsys.readouterr().out
    # example jobs print their own stdout first; the report is the final
    # top-level JSON object
    report = json.loads(out[out.rindex('{\n  "benchmark"'):])
    assert report["benchmark"] == "gridmix-lite"
    assert report["succeeded"]
