"""End-to-end jobs through the TPU map runner (CPU backend in tests; the
runner/kernels are backend-agnostic JAX). This is the seam the reference
exercised only by hand (SURVEY.md §4.8: zero GPU tests) — here it's the
deterministic path: run_on_tpu tasks select TpuMapRunner exactly like
MapTask.java:433-438 selects PipesGPUMapRunner."""

import numpy as np

from tpumr.core.counters import BackendCounter, TaskCounter
from tpumr.fs import get_filesystem
from tpumr.mapred import JobConf, Reducer, run_job
from tpumr.mapred.input_formats import DenseInputFormat


class CentroidReducer(Reducer):
    """Sums (partial_sum, count) pairs into a new centroid."""

    def reduce(self, key, values, output, reporter):
        total = None
        n = 0
        for s, c in values:
            total = s if total is None else total + s
            n += c
        output.collect(key, (total / max(1, n)).tolist())


def _save_npy(fs, path, arr):
    import io
    buf = io.BytesIO()
    np.save(buf, arr)
    fs.write_bytes(path, buf.getvalue())


def test_kmeans_job_on_tpu_runner():
    from tpumr.ops.kmeans import clear_centroid_cache
    clear_centroid_cache()
    fs = get_filesystem("mem:///")
    rng = np.random.default_rng(42)
    # three well-separated blobs
    blobs = np.concatenate([
        rng.normal(loc=c, scale=0.1, size=(50, 2))
        for c in [(0, 0), (5, 5), (-5, 5)]
    ]).astype(np.float32)
    rng.shuffle(blobs)
    _save_npy(fs, "/km/points.npy", blobs)
    cents = np.array([[0.5, 0.5], [4, 4], [-4, 4]], np.float32)
    _save_npy(fs, "/km/centroids.npy", cents)

    conf = JobConf()
    conf.set_input_paths("mem:///km/points.npy")
    conf.set_output_path("mem:///km/out")
    conf.set_input_format(DenseInputFormat)
    conf.set("tpumr.dense.split.rows", 40)
    conf.set("tpumr.kmeans.centroids", "mem:///km/centroids.npy")
    conf.set_map_kernel("kmeans-assign")
    conf.set_reducer_class(CentroidReducer)
    conf.set_num_reduce_tasks(1)
    conf.set("tpumr.local.run.on.tpu", True)

    result = run_job(conf)
    assert result.successful
    # backend counters prove TPU-runner placement
    assert result.counters.value(BackendCounter.GROUP,
                                 BackendCounter.TPU_MAP_TASKS) == result.num_maps
    assert result.counters.value(BackendCounter.GROUP,
                                 BackendCounter.CPU_MAP_TASKS) == 0
    assert result.counters.value(BackendCounter.GROUP,
                                 BackendCounter.TPU_DEVICE_BYTES_STAGED) > 0
    assert result.counters.value(TaskCounter.FRAMEWORK_GROUP,
                                 TaskCounter.MAP_INPUT_RECORDS) == 150

    lines = fs.read_bytes("mem:///km/out/part-00000").decode().splitlines()
    got = {}
    for ln in lines:
        k, v = ln.split("\t")
        got[int(k)] = eval(v)  # list literal
    assert len(got) == 3
    for cid, target in [(0, (0, 0)), (1, (5, 5)), (2, (-5, 5))]:
        np.testing.assert_allclose(got[cid], target, atol=0.2)


def test_same_job_runs_on_cpu_mapper():
    """The same K-Means job with run-on-tpu off uses the CPU mapper — the
    dual-backend contract the hybrid scheduler depends on."""
    from tpumr.ops.kmeans import KMeansCpuMapper, clear_centroid_cache
    clear_centroid_cache()
    fs = get_filesystem("mem:///")
    pts = np.array([[0.1, 0], [4.9, 5], [0, 0.2], [5, 4.8]], np.float32)
    _save_npy(fs, "/km2/points.npy", pts)
    _save_npy(fs, "/km2/centroids.npy", np.array([[0, 0], [5, 5]], np.float32))

    conf = JobConf()
    conf.set_input_paths("mem:///km2/points.npy")
    conf.set_output_path("mem:///km2/out")
    conf.set_input_format(DenseInputFormat)
    conf.set("tpumr.kmeans.centroids", "mem:///km2/centroids.npy")
    conf.set_mapper_class(KMeansCpuMapper)
    conf.set_reducer_class(CentroidReducer)
    conf.set_num_reduce_tasks(1)

    result = run_job(conf)
    assert result.successful
    assert result.counters.value(BackendCounter.GROUP,
                                 BackendCounter.CPU_MAP_TASKS) > 0
    assert result.counters.value(BackendCounter.GROUP,
                                 BackendCounter.TPU_MAP_TASKS) == 0


def test_wordcount_kernel_job_via_record_reader():
    """Text input has no read_batch: the runner drains the record reader
    into a RecordBatch. Input-record counting must not double-count."""
    fs = get_filesystem("mem:///")
    fs.write_bytes("/wc/in.txt", b"alpha beta\nbeta gamma\n" * 10)
    conf = JobConf()
    conf.set_input_paths("mem:///wc/in.txt")
    conf.set_output_path("mem:///wc/out")
    conf.set_map_kernel("wordcount")

    class Sum(__import__("tpumr.mapred.api", fromlist=["Reducer"]).Reducer):
        def reduce(self, key, values, output, reporter):
            output.collect(key, sum(values))

    conf.set_reducer_class(Sum)
    conf.set_num_reduce_tasks(1)
    conf.set("tpumr.local.run.on.tpu", True)
    result = run_job(conf)
    assert result.successful
    assert result.counters.value(TaskCounter.FRAMEWORK_GROUP,
                                 TaskCounter.MAP_INPUT_RECORDS) == 20
    out = dict(ln.split("\t") for ln in
               fs.read_bytes("mem:///wc/out/part-00000").decode().splitlines())
    assert out == {"alpha": "10", "beta": "20", "gamma": "10"}


def _kmeans_conf(fs, tag, n=150, rows_per_split=40):
    rng = np.random.default_rng(42)
    pts = rng.normal(size=(n, 2)).astype(np.float32)
    _save_npy(fs, f"/{tag}/points.npy", pts)
    _save_npy(fs, f"/{tag}/centroids.npy",
              np.array([[0, 0], [5, 5], [-5, 5]], np.float32))
    conf = JobConf()
    conf.set_input_paths(f"mem:///{tag}/points.npy")
    conf.set_output_path(f"mem:///{tag}/out")
    conf.set_input_format(DenseInputFormat)
    conf.set("tpumr.dense.split.rows", rows_per_split)
    conf.set("tpumr.kmeans.centroids", f"mem:///{tag}/centroids.npy")
    conf.set_map_kernel("kmeans-assign")
    conf.set_reducer_class(CentroidReducer)
    conf.set_num_reduce_tasks(1)
    conf.set("tpumr.local.run.on.tpu", True)
    return conf


def test_pipelined_window_fetches_once_per_window(monkeypatch):
    """The map phase of a kernel job batches ALL tasks' device→host
    transfers into one jax.device_get per pipeline window — on a tunneled
    TPU each fetch of a computed array is a full network roundtrip, so
    roundtrips per job must be O(tasks/window), not O(tasks)."""
    import jax

    from tpumr.ops.kmeans import clear_centroid_cache
    clear_centroid_cache()
    fs = get_filesystem("mem:///")
    calls = []
    real = jax.device_get
    monkeypatch.setattr(jax, "device_get", lambda x: (calls.append(1), real(x))[1])

    conf = _kmeans_conf(fs, "pw", n=150, rows_per_split=40)  # 4 splits
    result = run_job(conf)
    assert result.successful
    assert result.num_maps == 4
    assert len(calls) == 1  # one window, one roundtrip

    # window smaller than the task count: one fetch per window
    calls.clear()
    clear_centroid_cache()
    conf2 = _kmeans_conf(fs, "pw2", n=150, rows_per_split=40)
    conf2.set("tpumr.tpu.pipeline.window", 2)
    result2 = run_job(conf2)
    assert result2.successful
    assert len(calls) == 2  # ceil(4/2)


def test_pipeline_window_byte_budget_closes_window_early(monkeypatch):
    """The window is byte-bounded: staged inputs stay device-resident
    until the window fetch, so a tiny budget must split one count-window
    into several fetches (and still produce a correct job)."""
    import jax

    from tpumr.ops.kmeans import clear_centroid_cache
    clear_centroid_cache()
    fs = get_filesystem("mem:///")
    calls = []
    real = jax.device_get
    monkeypatch.setattr(jax, "device_get", lambda x: (calls.append(1), real(x))[1])

    conf = _kmeans_conf(fs, "pb", n=150, rows_per_split=40)  # 4 splits
    conf.set("tpumr.tpu.pipeline.window.mb", 0)  # every task busts the budget
    conf.set("tpumr.tpu.split.cache", False)
    result = run_job(conf)
    assert result.successful
    assert len(calls) == 4  # one-task windows


def test_pipelined_window_output_matches_per_task_path():
    """Window on vs off (window=0 forces the per-task path) produce
    byte-identical job output."""
    from tpumr.ops.kmeans import clear_centroid_cache
    fs = get_filesystem("mem:///")

    outs = []
    for i, window in enumerate((32, 0)):
        clear_centroid_cache()
        conf = _kmeans_conf(fs, f"pe{i}")
        conf.set("tpumr.tpu.pipeline.window", window)
        assert run_job(conf).successful
        outs.append(fs.read_bytes(f"mem:///pe{i}/out/part-00000"))
    assert outs[0] == outs[1]


def test_pi_kernel_launch_drain_stays_on_device_until_fetch():
    """pi-sampler's launch dispatches every sample block without a sync;
    records appear only at drain, and totals match the sample count."""
    from tpumr.mapred.split import InputSplit
    from tpumr.ops import get_kernel
    import jax

    kernel = get_kernel("pi-sampler")
    assert type(kernel).supports_launch()

    class B:
        num_records = 3
        def value(self, i):
            return f"{i} 1000".encode()

    conf = JobConf()
    state = kernel.map_batch_launch(B(), conf, None)
    out = dict(kernel.map_batch_drain(jax.device_get(state), conf, None))
    assert out["total"] == 3000
    assert 0 < out["inside"] <= 3000


def test_pipeline_window_kernel_error_fails_job_cleanly():
    """A kernel that raises mid-window must fail the job with the real
    error (no hang, no partial commit)."""
    import pytest

    from tpumr.ops.registry import KernelMapper, register_kernel

    class BoomKernel(KernelMapper):
        name = "boom-on-third"
        calls = [0]

        def map_batch_launch(self, batch, conf, task):
            self.calls[0] += 1
            if self.calls[0] == 3:
                raise RuntimeError("kernel exploded on split 3")
            import jax.numpy as jnp
            return (jnp.zeros(2),)

        def map_batch_drain(self, fetched, conf, task):
            yield 0, float(fetched[0][0])

    register_kernel(BoomKernel())
    fs = get_filesystem("mem:///")
    pts = np.zeros((160, 2), np.float32)
    import io as _io
    buf = _io.BytesIO()
    np.save(buf, pts)
    fs.write_bytes("/bw/points.npy", buf.getvalue())
    conf = JobConf()
    conf.set_input_paths("mem:///bw/points.npy")
    conf.set_output_path("mem:///bw/out")
    conf.set_input_format(DenseInputFormat)
    conf.set("tpumr.dense.split.rows", 40)  # 4 splits, one window
    conf.set_map_kernel("boom-on-third")
    conf.set_num_reduce_tasks(0)
    conf.set("tpumr.local.run.on.tpu", True)
    with pytest.raises(RuntimeError, match="kernel exploded"):
        run_job(conf)
    assert not fs.exists("mem:///bw/out/part-00000")  # nothing committed


def test_hbm_split_cache_hit_on_second_round():
    """Iterative jobs stage each dense split once: round 2 reports zero
    newly-staged device bytes (HBM-resident split cache)."""
    from tpumr.mapred.tpu_runner import clear_split_caches, _split_caches
    from tpumr.ops.kmeans import clear_centroid_cache
    clear_split_caches()
    clear_centroid_cache()
    fs = get_filesystem("mem:///")
    pts = np.random.default_rng(7).normal(size=(64, 2)).astype(np.float32)
    _save_npy(fs, "/kc/points.npy", pts)
    _save_npy(fs, "/kc/centroids.npy", np.eye(2, dtype=np.float32))

    def round_conf(i):
        conf = JobConf()
        conf.set_input_paths("mem:///kc/points.npy")
        conf.set_output_path(f"mem:///kc/out{i}")
        conf.set_input_format(DenseInputFormat)
        conf.set("tpumr.kmeans.centroids", "mem:///kc/centroids.npy")
        conf.set_map_kernel("kmeans-assign")
        conf.set_reducer_class(CentroidReducer)
        conf.set_num_reduce_tasks(1)
        conf.set("tpumr.local.run.on.tpu", True)
        return conf

    r1 = run_job(round_conf(1))
    staged1 = r1.counters.value(BackendCounter.GROUP,
                                BackendCounter.TPU_DEVICE_BYTES_STAGED)
    assert staged1 == pts.nbytes
    r2 = run_job(round_conf(2))
    staged2 = r2.counters.value(BackendCounter.GROUP,
                                BackendCounter.TPU_DEVICE_BYTES_STAGED)
    assert staged2 == 0
    assert any(c.hits > 0 for c in _split_caches.values())
    clear_split_caches()
