"""Multi-host (DCN) bring-up executed for real: TWO separate processes
join one jax.distributed job through parallel/multihost.ensure_initialized
and run a cross-process collective over the global mesh (SURVEY.md §5
distributed-comm TPU-native equivalent — here on CPU devices, both
processes on one machine, which exercises the identical code path the
DCN deployment uses)."""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
rank = int(sys.argv[1]); coord = sys.argv[2]
import jax
jax.config.update("jax_platforms", "cpu")
from tpumr.mapred.jobconf import JobConf
from tpumr.parallel import multihost
conf = JobConf()
conf.set("tpumr.distributed.coordinator", coord)
conf.set("tpumr.distributed.num.processes", 2)
conf.set("tpumr.distributed.process.id", rank)
assert multihost.ensure_initialized(conf) is True
pi, pc = multihost.process_info()
assert (pi, pc) == (rank, 2), (pi, pc)
mesh = multihost.global_mesh(conf)
assert len(mesh.devices.flatten()) == 4, mesh
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map
from tpumr.parallel import collectives
local = np.array([rank * 2 + 0.0, rank * 2 + 1.0], dtype=np.float32)
garr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), local, (4,))
out = jax.jit(shard_map(lambda x: collectives.psum(x, "data"),
                        mesh=mesh, in_specs=P("data"), out_specs=P()))(garr)
total = float(np.asarray(jax.device_get(out))[0])
assert total == 6.0, total
print("RANK%d OK" % rank, flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_distributed_bringup():
    """ensure_initialized + global_mesh + a psum spanning two OS
    processes: the full DCN code path (jax.distributed coordinator,
    cross-process collective) actually executes."""
    prog = WORKER.format(repo=REPO)
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)   # workers set their own device count
    procs = [subprocess.Popen([sys.executable, "-c", prog, str(r), coord],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=200)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"distributed bring-up hung; partial: {outs}")
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank{r} failed:\n{out[-2000:]}"
        assert f"RANK{r} OK" in out
