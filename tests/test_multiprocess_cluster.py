"""Multi-process cluster smoke test ≈ TestMiniMRWithDFS over REAL process
boundaries: NameNode, DataNode, JobMaster, and two NodeRunners launched as
separate OS processes via ``python -m tpumr.cli`` (the bin/hadoop analog,
reference bin/hadoop:66-95 + hadoop-daemon.sh), then a wordcount submitted
from this process with tdfs:// input and output.

This is the seam the in-process MiniMRCluster cannot cover: daemon arg
parsing, conf propagation through -D generic options, RPC (authenticated
with a shared secret) across real process boundaries, tdfs reads/writes
from tracker processes, and job history written by the master daemon.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow

SECRET = "smoke-secret"


class Daemon:
    """One `python -m tpumr.cli <cmd>` child; parses its startup banner."""

    def __init__(self, name, args, banner):
        self.name = name
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "tpumr.cli"] + args,
            cwd=REPO, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True)
        self.banner = banner
        self.banner_line = None
        self.lines = []
        self._reader = threading.Thread(target=self._drain, daemon=True)
        self._reader.start()

    def _drain(self):
        for line in self.proc.stderr:
            self.lines.append(line.rstrip())
            if self.banner in line and self.banner_line is None:
                self.banner_line = line.strip()

    def wait_up(self, timeout=30.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.banner_line is not None:
                return self.banner_line
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"{self.name} died rc={self.proc.returncode}:\n"
                    + "\n".join(self.lines[-20:]))
            time.sleep(0.05)
        raise TimeoutError(f"{self.name} never printed {self.banner!r}:\n"
                           + "\n".join(self.lines[-20:]))

    def stop(self):
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()


def _port_from(line, prefix_split):
    # e.g. "NameNode up at tdfs://127.0.0.1:38291/" -> 38291
    frag = line.split(prefix_split, 1)[1]
    return int(frag.split("/", 1)[0].rsplit(":", 1)[1])


@pytest.fixture(scope="module")
def cluster_procs(tmp_path_factory):
    work = tmp_path_factory.mktemp("mpsmoke")
    daemons = []
    common = ["-D", f"tpumr.rpc.secret={SECRET}",
              "-D", "dfs.replication=1",
              "-D", "tpumr.heartbeat.interval.ms=200"]
    try:
        nn = Daemon("namenode", common + [
            "namenode", "-dir", str(work / "name"), "-port", "0"],
            "NameNode up at ")
        daemons.append(nn)
        nn_port = _port_from(nn.wait_up(), "tdfs://")

        dn = Daemon("datanode", common + [
            "datanode", "-nn", f"127.0.0.1:{nn_port}",
            "-dir", str(work / "data")], "DataNode up ")
        daemons.append(dn)
        dn.wait_up()

        jt = Daemon("jobtracker", common + [
            "-D", f"tpumr.history.dir={work / 'history'}",
            "-D", f"fs.default.name=tdfs://127.0.0.1:{nn_port}/",
            "jobtracker", "-port", "0"], "JobMaster up at ")
        daemons.append(jt)
        jt_port = _port_from(jt.wait_up() + "/", "up at ")

        for i in range(2):
            tt = Daemon(f"tasktracker{i}", common + [
                "-D", "mapred.tasktracker.map.cpu.tasks.maximum=2",
                "-D", f"mapred.local.dir={work / f'local{i}'}",
                "tasktracker", "-jt", f"127.0.0.1:{jt_port}"],
                "NodeRunner up")
            daemons.append(tt)
            tt.wait_up()

        yield {"nn_port": nn_port, "jt_port": jt_port, "work": work}
    finally:
        for d in reversed(daemons):
            d.stop()


def _client_conf(cluster_procs):
    from tpumr.mapred.jobconf import JobConf
    conf = JobConf()
    conf.set("tpumr.rpc.secret", SECRET)
    conf.set("dfs.replication", 1)
    conf.set("fs.default.name",
             f"tdfs://127.0.0.1:{cluster_procs['nn_port']}/")
    conf.set("mapred.job.tracker", f"127.0.0.1:{cluster_procs['jt_port']}")
    return conf


def test_wordcount_across_real_processes(cluster_procs):
    from tpumr.fs import get_filesystem
    from tpumr.mapred.job_client import JobClient

    conf = _client_conf(cluster_procs)
    nn = cluster_procs["nn_port"]
    fs = get_filesystem(f"tdfs://127.0.0.1:{nn}/", conf)
    fs.mkdirs("/smoke")
    fs.write_bytes("/smoke/in.txt", b"alpha beta\nbeta gamma\n" * 100)

    jconf = _client_conf(cluster_procs)
    jconf.set_job_name("mp-smoke-wordcount")
    jconf.set_input_paths(f"tdfs://127.0.0.1:{nn}/smoke/in.txt")
    jconf.set_output_path(f"tdfs://127.0.0.1:{nn}/smoke/out")
    jconf.set("mapred.mapper.class",
              "tpumr.ops.wordcount.WordCountCpuMapper")
    jconf.set("mapred.reducer.class",
              "tpumr.examples.basic.LongSumReducer")
    jconf.set("mapred.min.split.size", 1)
    jconf.set("mapred.map.tasks", 2)
    jconf.set_num_reduce_tasks(2)

    result = JobClient(jconf).run_job(jconf)
    assert result.successful

    counts = {}
    parts = 0
    for st in fs.list_files("/smoke/out"):
        if st.path.name.startswith("part-"):
            parts += 1
            for line in fs.read_bytes(st.path).decode().splitlines():
                k, v = line.split("\t")
                counts[k] = int(v)
    assert parts == 2
    assert counts == {"alpha": 100, "beta": 200, "gamma": 100}

    # history written by the MASTER process, one JOB_FINISHED event
    hist_dir = cluster_procs["work"] / "history"
    hist_files = list(hist_dir.glob("job_*.jsonl"))
    assert hist_files, "job tracker process wrote no history"
    events = [json.loads(line)
              for f in hist_files for line in f.read_text().splitlines()]
    kinds = {e.get("event") for e in events}
    assert "JOB_FINISHED" in kinds or "JOB_SUBMITTED" in kinds, kinds

    # the `tpumr job -list` CLI sees the finished job from yet another
    # process (folded in here so the assertion does not depend on test
    # ordering — the module fixture starts a master with zero jobs)
    _assert_job_cli_lists(cluster_procs)


def _assert_job_cli_lists(cluster_procs):
    """`tpumr job -list` (the bin/hadoop job analog) against the live
    master daemon — exercises the client CLI over the same secret."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    r = subprocess.run(
        [sys.executable, "-m", "tpumr.cli",
         "-D", f"tpumr.rpc.secret={SECRET}",
         "-jt", f"127.0.0.1:{cluster_procs['jt_port']}",
         "job", "-list"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "job_" in r.stdout
    assert "SUCCEEDED" in r.stdout


def test_isolated_tasks_with_job_tokens_across_processes(cluster_procs):
    """Process-isolated attempts over the authenticated multiprocess
    cluster: the child process (grandchild of the tracker DAEMON
    process) signs its umbilical + shuffle traffic with only its JOB
    token — the full credential-scoping chain across real process
    boundaries."""
    from tpumr.fs import get_filesystem
    from tpumr.mapred.job_client import JobClient

    conf = _client_conf(cluster_procs)
    nn = cluster_procs["nn_port"]
    fs = get_filesystem(f"tdfs://127.0.0.1:{nn}/", conf)
    fs.mkdirs("/iso")
    fs.write_bytes("/iso/in.txt", b"tok a tok\nb tok\n" * 50)

    jconf = _client_conf(cluster_procs)
    jconf.set_job_name("mp-isolated")
    jconf.set("tpumr.task.isolation", "process")
    jconf.set_input_paths(f"tdfs://127.0.0.1:{nn}/iso/in.txt")
    jconf.set_output_path(f"tdfs://127.0.0.1:{nn}/iso/out")
    jconf.set("mapred.mapper.class",
              "tpumr.ops.wordcount.WordCountCpuMapper")
    jconf.set("mapred.reducer.class",
              "tpumr.examples.basic.LongSumReducer")
    jconf.set_num_reduce_tasks(1)

    result = JobClient(jconf).run_job(jconf)
    assert result.successful
    counts = {}
    for st in fs.list_files("/iso/out"):
        if st.path.name.startswith("part-"):
            for line in fs.read_bytes(st.path).decode().splitlines():
                k, v = line.split("\t")
                counts[k] = int(v)
    assert counts == {"tok": 150, "a": 50, "b": 50}
    # positive proof a CHILD PROCESS actually ran (the isolation path,
    # not an in-process fallback): process_runner writes child.log into
    # the tracker daemons' userlogs trees unconditionally
    child_logs = list(cluster_procs["work"].glob(
        "local*/*/userlogs/job_*/attempt_*/child.log"))
    assert child_logs, "no isolated child ever ran"
