"""Continuous profiler + flight recorder (tpumr/metrics/sampler.py,
tpumr/metrics/flightrec.py): trie bounding, subsystem classification,
self-exclusion, folded round-trips, the sampler's overhead bound, the
SLO-breach incident pipeline end-to-end, and the /threads, /stacks,
/flame, and ``tpumr prof`` surfaces."""

import json
import os
import shutil
import threading
import time
import urllib.request

import pytest

from tpumr.mapred.jobconf import JobConf
from tpumr.metrics.flightrec import (FlightRecorder, typed_p99,
                                     validate_incident)
from tpumr.metrics.locks import InstrumentedRLock, lock_table
from tpumr.metrics.sampler import (StackSampler, StackTrie, classify,
                                   flame_svg, is_idle, parse_folded,
                                   render_folded, threads_dump)


def fetch(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class TestStackTrie:
    def test_canonical_passthrough_and_counts(self):
        t = StackTrie(max_nodes=100)
        s = ("m:a", "m:b", "m:c")
        assert t.add(s) == s
        assert t.add(s) == s
        assert dict(t.folded())[("m:a", "m:b", "m:c")] == 2

    def test_node_budget_truncates_visibly(self):
        t = StackTrie(max_nodes=10)
        for i in range(50):
            t.add((f"m:root{i}", f"m:leaf{i}"))
        # bounded: budget nodes plus at most one (other) child per level
        assert t.nodes <= 2 * t.max_nodes
        folded = t.folded()
        assert any(StackTrie.OTHER in stack for stack, _ in folded), \
            "overflow must be visible in the output, not dropped"
        # total count is conserved through truncation
        assert sum(c for _, c in folded) == 50

    def test_deep_recursion_truncates_at_depth_limit(self):
        from tpumr.metrics.sampler import MAX_STACK_DEPTH
        parked = threading.Event()
        done = threading.Event()

        def recurse(n):
            if n:
                return recurse(n - 1)
            parked.set()
            done.wait(10)

        t = threading.Thread(target=recurse,
                             args=(MAX_STACK_DEPTH + 50,),
                             name="deep-thread", daemon=True)
        t.start()
        assert parked.wait(5)
        s = StackSampler(hz=97).start()
        try:
            time.sleep(0.2)
            pairs = parse_folded(s.folded(thread_prefix="deep-thread"))
        finally:
            s.stop()
            done.set()
        assert pairs
        # a runaway recursion samples as a bounded stack, not an
        # unbounded allocation (thread-name root + MAX_STACK_DEPTH)
        assert all(len(stack) <= MAX_STACK_DEPTH + 1
                   for stack, _ in pairs)


class TestClassify:
    def test_reactor_wins_by_thread_identity(self):
        # even mid-dispatch into jobtracker code, the reactor's samples
        # are the loop's, never the dispatched subsystem's
        s = ("tpumr.ipc.rpc:_serve", "tpumr.mapred.jobtracker:heartbeat")
        assert classify(s, "rpc-reactor") == "reactor"

    def test_assign_beats_fold_innermost_out(self):
        # both frames live in one rpc-handler stack during a beat's
        # assign pass; the deeper scheduler frame owns the sample
        s = ("tpumr.mapred.jobtracker:heartbeat",
             "tpumr.mapred.jobtracker:_heartbeat_fold_and_assign",
             "tpumr.mapred.scheduler:assign_tasks")
        assert classify(s, "rpc-handler_3") == "assign"
        # without the scheduler frame the same thread is folding
        assert classify(s[:2], "rpc-handler_3") == "fold"

    def test_history_and_roles_and_other(self):
        assert classify(("tpumr.mapred.history:append",),
                        "history-writer") == "history"
        # no module match -> thread role
        assert classify(("tpumr.ipc.rpc:_dispatch",),
                        "rpc-handler_0") == "rpc"
        assert classify(("x:y",), "mystery") == "other"

    def test_idle_leaves(self):
        assert is_idle(("tpumr.scale.simtracker:_worker",
                        "threading:wait"))
        assert is_idle(("tpumr.ipc.rpc:_serve", "selectors:select"))
        assert is_idle(("tpumr.ipc.rpc:call", "tpumr.ipc.rpc:_fill"))
        assert not is_idle(("tpumr.mapred.jobtracker:heartbeat",))


class TestFolded:
    def test_round_trip(self):
        pairs = [(("main", "m:a", "m:b"), 3), (("worker", "m:c"), 1)]
        text = render_folded(pairs)
        assert parse_folded(text) == sorted(pairs)

    def test_flame_svg_self_contained(self):
        svg = flame_svg(render_folded([(("main", "m:a", "m:b"), 5),
                                       (("main", "m:a", "m:c"), 3)]),
                        title="t")
        assert svg.startswith("<svg") and svg.endswith("</svg>")
        assert "<script" not in svg
        assert "m:b" in svg and "m:c" in svg

    def test_flame_svg_empty_window(self):
        assert "no samples" in flame_svg("", title="t")


class TestSampler:
    def test_samples_busy_thread_and_excludes_self(self):
        stop = threading.Event()

        def burn():
            x = 0
            while not stop.is_set():
                x += 1
            return x

        t = threading.Thread(target=burn, name="burner", daemon=True)
        t.start()
        s = StackSampler(hz=97).start()
        try:
            time.sleep(0.6)
            folded = s.folded()
        finally:
            s.stop()
            stop.set()
            t.join()
        pairs = parse_folded(folded)
        roots = {stack[0] for stack, _ in pairs}
        assert "burner" in roots
        # the sampler's own threads never appear in their own samples
        assert not any(r.startswith("prof-") for r in roots)
        shares = s.subsystem_shares()
        assert abs(sum(shares.values()) - 1.0) < 1e-9
        assert shares["other"] > 0  # the burner

    def test_thread_prefix_filter(self):
        stop = threading.Event()
        ts = [threading.Thread(target=stop.wait, name=f"task-a{i}",
                               daemon=True) for i in range(2)]
        for t in ts:
            t.start()
        s = StackSampler(hz=97).start()
        try:
            time.sleep(0.3)
            only = parse_folded(s.folded(thread_prefix="task-a0"))
        finally:
            s.stop()
            stop.set()
        assert only and all(stack[0] == "task-a0" for stack, _ in only)

    def test_from_conf_gating(self):
        conf = JobConf()
        assert StackSampler.from_conf(conf) is None
        conf.set("tpumr.prof.enabled", True)
        s = StackSampler.from_conf(conf)
        assert s is not None and s.hz == 19

    def test_overhead_within_bound(self):
        """Sampling at the default hz must not cost more than ~10% of a
        CPU-bound workload's wall time (the always-on contract)."""

        def work():
            x = 0
            for i in range(600_000):
                x += i * i
            return x

        def best_of(n):
            return min(_timed(work) for _ in range(n))

        def _timed(fn):
            t0 = time.perf_counter()
            fn()
            return time.perf_counter() - t0

        base = best_of(3)
        s = StackSampler(hz=19).start()
        try:
            sampled = best_of(3)
        finally:
            s.stop()
        assert sampled <= base * 1.10 + 0.005, \
            f"sampler overhead too high: {base:.4f}s -> {sampled:.4f}s"
        # and the sampler's own accounting agrees it is cheap
        snap = s.registry.snapshot()
        assert snap["prof_overhead_share"] < 0.05


class TestLockTable:
    def test_holder_and_waiter_visible(self):
        lk = InstrumentedRLock(name="t_lock_table", rank=45)
        got = threading.Event()
        release = threading.Event()

        def holder():
            with lk:
                got.set()
                release.wait(5)

        h = threading.Thread(target=holder, name="holder-thread",
                             daemon=True)
        h.start()
        assert got.wait(5)
        waiting = threading.Thread(
            target=lambda: lk.acquire(timeout=5) and lk.release(),
            name="waiter-thread", daemon=True)
        waiting.start()
        deadline = time.monotonic() + 5
        row = None
        while time.monotonic() < deadline:
            rows = {r["name"]: r for r in lock_table()}
            row = rows.get("t_lock_table")
            if row and row["waiters"]:
                break
            time.sleep(0.01)
        assert row is not None
        assert row["holder"] == "holder-thread"
        assert "waiter-thread" in row["waiters"]
        assert row["held_for_s"] >= 0
        release.set()
        h.join(5)
        waiting.join(5)
        rows = {r["name"]: r for r in lock_table()}
        assert rows["t_lock_table"]["holder"] is None

    def test_threads_dump_annotates(self):
        lk = InstrumentedRLock(name="t_dump_lock", rank=46)
        with lk:
            text = threads_dump()
        assert "== locks (rank order) ==" in text
        assert "t_dump_lock" in text
        assert "MainThread" in text


class TestTypedP99:
    def test_interpolates_buckets(self):
        # sparse {bucket_index: count} over bounds, Histogram.typed()
        # shape: all observations in (0.1, 0.2] -> p99 inside it
        t = {"bounds": [0.1, 0.2, 0.4], "buckets": {1: 100},
             "count": 100, "max": 0.2}
        v = typed_p99(t)
        assert 0.1 < v <= 0.2

    def test_empty_and_overflow(self):
        assert typed_p99({"bounds": [], "buckets": {}, "count": 0}) == 0.0
        # index len(bounds) is the +Inf bucket -> p99 reports max
        t = {"bounds": [0.1], "buckets": {1: 10}, "count": 10,
             "max": 3.0}
        assert typed_p99(t) == 3.0

    def test_windowed_via_typed_delta(self):
        from tpumr.metrics.histogram import Histogram, typed_delta
        h = Histogram("hb", bounds=[0.05, 0.1, 0.5, 1.0])
        for _ in range(50):
            h.observe(0.01)
        prev = h.typed()
        for _ in range(50):
            h.observe(0.7)   # the breach happens AFTER the snapshot
        d = typed_delta(h.typed(), prev)
        # the delta window sees only the slow half -> p99 lands high
        assert typed_p99(d) > 0.5


@pytest.fixture(scope="module")
def prof_cluster(tmp_path_factory):
    """One mini cluster with the profiler on and a forced-slow master
    heartbeat: the flight-recorder e2e substrate."""
    from tpumr.mapred.mini_cluster import MiniMRCluster
    inc_dir = str(tmp_path_factory.mktemp("incidents"))
    conf = JobConf()
    conf.set("mapred.job.tracker.http.port", 0)
    conf.set("mapred.task.tracker.http.port", 0)
    conf.set("tpumr.prof.enabled", True)
    conf.set("tpumr.prof.incident.dir", inc_dir)
    conf.set("tpumr.prof.incident.slo.ms", 250)
    conf.set("tpumr.prof.incident.cooldown.ms", 600_000)
    # the observability seam: stall the first 3 beats past the SLO
    conf.set("tpumr.fi.jt.heartbeat.slow.probability", 1.0)
    conf.set("tpumr.fi.jt.heartbeat.slow.max.failures", 3)
    conf.set("tpumr.fi.jt.heartbeat.slow.ms", 400)
    with MiniMRCluster(num_trackers=1, cpu_slots=1, tpu_slots=0,
                       conf=conf) as c:
        c.incident_dir = os.path.join(inc_dir, "incidents")
        yield c


class TestIncidentE2E:
    def _wait_incidents(self, cluster, timeout=15.0):
        url = cluster.master.http_url + "/json/incidents"
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            _, body = fetch(url)
            rows = json.loads(body)
            if rows:
                return rows
            time.sleep(0.25)
        raise AssertionError("no incident within deadline")

    def test_breach_writes_exactly_one_valid_bundle(self, cluster_env):
        cluster = cluster_env
        rows = self._wait_incidents(cluster)
        # the seam stalled 3 beats but the cooldown admits ONE bundle
        time.sleep(2.5)   # two more recorder ticks under breach
        _, body = fetch(cluster.master.http_url + "/json/incidents")
        rows = json.loads(body)
        assert len(rows) == 1, rows
        assert rows[0]["reason"][0]["metric"] == "heartbeat_seconds"
        _, body = fetch(cluster.master.http_url
                        + f"/incident?name={rows[0]['name']}")
        doc = json.loads(body)
        assert validate_incident(doc) == []
        assert doc["reason"][0]["p99_s"] > doc["slo_ms"] / 1000.0
        # the bundle carries every forensic section with real content
        assert doc["folded_stacks"].strip()
        assert doc["heartbeat"]["trackers"] == 1
        assert "rpc_inflight" in doc["rpc"]
        # suppressed repeats are counted, not silently dropped
        _, body = fetch(cluster.master.http_url + "/json/metrics")
        prof = json.loads(body).get("prof", {})
        assert prof.get("incidents_written") == 1
        # export for the CI artifact when asked: the bundle itself plus
        # the master's live folded-stack window (flamegraph.pl-ready)
        out = os.environ.get("TPUMR_INCIDENT_E2E_OUT")
        if out:
            os.makedirs(out, exist_ok=True)
            shutil.copy(
                os.path.join(cluster.incident_dir, rows[0]["name"]), out)
            _, folded = fetch(cluster.master.http_url + "/stacks")
            with open(os.path.join(out, "master-stacks.folded"),
                      "w") as f:
                f.write(folded)

    @pytest.fixture()
    def cluster_env(self, prof_cluster):
        return prof_cluster

    def test_incidents_page_lists_bundle(self, cluster_env):
        rows = self._wait_incidents(cluster_env)
        status, page = fetch(cluster_env.master.http_url + "/incidents")
        assert status == 200
        assert rows[0]["name"] in page
        assert "heartbeat_seconds" in page

    def test_incident_name_traversal_rejected(self, cluster_env):
        self._wait_incidents(cluster_env)
        status, _ = fetch(cluster_env.master.http_url
                          + "/incident?name=../../etc/passwd")
        assert status >= 400


class TestHttpSurfaces:
    def test_master_stacks_flame_threads(self, prof_cluster):
        base = prof_cluster.master.http_url
        time.sleep(0.3)
        status, stacks = fetch(base + "/stacks?seconds=30")
        assert status == 200
        assert parse_folded(stacks), "no samples in folded output"
        status, svg = fetch(base + "/flame")
        assert status == 200 and svg.startswith("<svg")
        status, dump = fetch(base + "/threads")
        assert status == 200
        assert "== locks (rank order) ==" in dump
        assert "rpc-reactor" in dump

    def test_threads_without_sampler(self):
        """/threads is universal — a daemon with profiling off still
        serves the instant dump."""
        from tpumr.mapred.jobtracker import JobMaster
        conf = JobConf()
        conf.set("mapred.job.tracker.http.port", 0)
        m = JobMaster(conf).start()
        try:
            status, dump = fetch(m.http_url + "/threads")
            assert status == 200 and "MainThread" in dump
            # but the sampler surfaces 404 (off by default)
            status, _ = fetch(m.http_url + "/stacks")
            assert status == 404
            status, page = fetch(m.http_url + "/incidents")
            assert status == 200 and "disabled" in page
        finally:
            m.stop()

    def test_cluster_page_profiler_line(self, prof_cluster):
        status, page = fetch(prof_cluster.master.http_url + "/cluster")
        assert status == 200
        assert "trace spans dropped" in page
        assert "sampler overhead" in page

    def test_tracker_attempt_filter_and_metrics(self, prof_cluster):
        tr = prof_cluster.trackers[0]
        base = tr._http.url
        status, stacks = fetch(base + "/stacks")
        assert status == 200
        # attempt filter returns cleanly even for a finished attempt
        status, filtered = fetch(base + "/stacks?attempt=nope")
        assert status == 200
        assert parse_folded(filtered) == []
        _, body = fetch(base + "/json/metrics")
        snap = json.loads(body)
        assert "prof" in snap, "tracker sampler registry not registered"
        assert any(k.startswith("cpu_share|subsystem=")
                   for k in snap["prof"])


class TestProfCli:
    def test_prof_pulls_folded_and_flame(self, prof_cluster, tmp_path,
                                         capsys):
        from tpumr.cli import main as cli_main
        hp = prof_cluster.master.http_url.split("//", 1)[1]
        assert cli_main(["prof", hp]) == 0
        out = capsys.readouterr().out
        assert parse_folded(out)
        svg_path = str(tmp_path / "f.svg")
        assert cli_main(["prof", hp, "-seconds", "30", "-flame",
                         "-out", svg_path]) == 0
        assert open(svg_path).read().startswith("<svg")

    def test_prof_404_mentions_knob(self, capsys):
        from tpumr.cli import main as cli_main
        from tpumr.mapred.jobtracker import JobMaster
        conf = JobConf()
        conf.set("mapred.job.tracker.http.port", 0)
        m = JobMaster(conf).start()
        try:
            hp = m.http_url.split("//", 1)[1]
            assert cli_main(["prof", hp]) == 1
            assert "tpumr.prof.enabled" in capsys.readouterr().err
        finally:
            m.stop()
