"""DAG-of-jobs pipeline engine (PR 11 tentpole).

Four legs:

- graph validation: cycles, dangling edges, duplicate node ids, and
  the stream-edge contract (reduces + SequenceFiles upstream) are
  rejected at submit, never half-run;
- fan-out / fan-in wiring over a real mini cluster: a diamond of jobs
  runs off ONE submission, downstream inputs wired to upstream
  committed outputs, stage jobs anchored at the pipeline's queue
  position;
- streamed stage handoff: the downstream stage fetches upstream reduce
  partitions over the shuffle wire (IFile framing, MapLocator over the
  handoff completion-event feed) and its final output is byte-identical
  to the DFS-staged chain;
- loop nodes: the convergence predicate settles early, the max-rounds
  cutoff bounds a never-converging loop, and the kmeans round driver
  versions its centroid file per round instead of rewriting one path
  (the devcache staleness fix — no per-round cache clears).
"""

import time

import numpy as np
import pytest

from tpumr.fs import FileSystem, get_filesystem
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.mini_cluster import MiniMRCluster
from tpumr.pipeline import JobGraph, PipelineClient, PipelineError
from tpumr.pipeline.graph import expand_round

# ------------------------------------------------------------ validation


def _conf(**kv):
    base = {"mapred.output.dir": "mem:///p/out"}
    base.update(kv)
    return base


class TestGraphValidation:
    def test_duplicate_node_id_rejected(self):
        g = JobGraph("g")
        g.node("a", _conf())
        with pytest.raises(PipelineError, match="duplicate"):
            g.node("a", _conf())

    def test_dangling_edge_rejected(self):
        g = JobGraph("g").node("a", _conf()).edge("a", "ghost")
        with pytest.raises(PipelineError, match="dangling"):
            g.validate()

    def test_cycle_rejected(self):
        g = (JobGraph("g")
             .node("a", _conf()).node("b", _conf()).node("c", _conf())
             .edge("a", "b").edge("b", "c").edge("c", "a"))
        with pytest.raises(PipelineError, match="cycle"):
            g.validate()

    def test_self_edge_rejected(self):
        g = JobGraph("g").node("a", _conf()).edge("a", "a")
        with pytest.raises(PipelineError, match="self-edge"):
            g.validate()

    def test_empty_graph_rejected(self):
        with pytest.raises(PipelineError, match="empty"):
            JobGraph("g").validate()

    def test_missing_output_dir_rejected(self):
        g = JobGraph("g").node("a", {"mapred.reduce.tasks": 1})
        with pytest.raises(PipelineError, match="output.dir"):
            g.validate()

    def test_stream_edge_requires_reduces(self):
        g = (JobGraph("g")
             .node("a", _conf(**{"mapred.reduce.tasks": 0}))
             .node("b", _conf())
             .edge("a", "b", stream=True))
        with pytest.raises(PipelineError, match="map-only"):
            g.validate()

    def test_stream_edge_requires_sequencefiles(self):
        g = (JobGraph("g")
             .node("a", _conf(**{"mapred.reduce.tasks": 1}))
             .node("b", _conf())
             .edge("a", "b", stream=True))
        with pytest.raises(PipelineError, match="SequenceFiles"):
            g.validate()

    def test_mixed_edge_modes_rejected(self):
        seq = {"mapred.output.format.class":
               "tpumr.mapred.output_formats.SequenceFileOutputFormat",
               "mapred.reduce.tasks": 1}
        g = (JobGraph("g")
             .node("a", _conf(**seq)).node("b", _conf(**seq))
             .node("c", _conf())
             .edge("a", "c", stream=True).edge("b", "c"))
        with pytest.raises(PipelineError, match="mixes"):
            g.validate()

    def test_loop_converge_spec_checked(self):
        with pytest.raises(PipelineError, match="missing"):
            (JobGraph("g")
             .loop("a", _conf(), max_rounds=2, converge={"op": "lt"})
             .validate())
        with pytest.raises(PipelineError, match="op"):
            (JobGraph("g")
             .loop("a", _conf(), max_rounds=2,
                   converge={"group": "G", "counter": "C", "op": "??",
                             "value": 1})
             .validate())

    def test_wire_round_trip(self):
        g = (JobGraph("g", conf={"user.name": "alice"})
             .node("a", _conf(**{"mapred.reduce.tasks": 1}))
             .loop("b", _conf(), max_rounds=3,
                   converge={"group": "G", "counter": "C", "op": "le",
                             "value": 0})
             .edge("a", "b"))
        g.validate()
        g2 = JobGraph.from_dict(g.to_dict())
        g2.validate()
        assert g2.to_dict() == g.to_dict()
        assert g2.topo_order() == ["a", "b"]

    def test_round_expansion(self):
        conf = {"in": "mem:///w/cents-r{round}.npy",
                "out": "mem:///w/cents-r{next_round}.npy",
                "prev": "{prev_round}", "n": 7}
        got = expand_round(conf, 4)
        assert got == {"in": "mem:///w/cents-r4.npy",
                       "out": "mem:///w/cents-r5.npy",
                       "prev": "3", "n": 7}


# ------------------------------------------------------------- cluster


def _cluster_conf():
    conf = JobConf()
    conf.set("mapred.reduce.slowstart.completed.maps", 0.0)
    conf.set("mapred.speculative.execution", False)
    return conf


def _write_words(fs, path, lines=600):
    fs.write_bytes(path, b"".join(b"w%02d x\n" % (i % 13)
                                  for i in range(lines)))


def _read_parts(fs, outdir):
    return b"".join(fs.read_bytes(st.path)
                    for st in sorted(fs.list_status(outdir),
                                     key=lambda s: str(s.path))
                    if "part-" in str(st.path))


def _count_conf(inpath, outdir, seq_out=True, reduces=2):
    conf = {
        "mapred.input.dir": inpath,
        "mapred.output.dir": outdir,
        "mapred.mapper.class": "tpumr.mapred.lib.TokenCountMapper",
        "mapred.reducer.class": "tpumr.examples.basic.LongSumReducer",
        "mapred.reduce.tasks": reduces,
        "mapred.map.tasks": 3,
    }
    if seq_out:
        conf["mapred.output.format.class"] = \
            "tpumr.mapred.output_formats.SequenceFileOutputFormat"
    return conf


def _emit_conf(outdir):
    """Map-only identity stage: (k, v) records straight to text."""
    return {
        "mapred.output.dir": outdir,
        "mapred.mapper.class": "tpumr.mapred.api.IdentityMapper",
        "mapred.reduce.tasks": 0,
    }


class TestPipelineCluster:
    def teardown_method(self):
        FileSystem.clear_cache()

    def test_dfs_diamond_runs_off_one_submission(self):
        with MiniMRCluster(num_trackers=2, tpu_slots=0,
                           conf=_cluster_conf()) as c:
            fs = get_filesystem("mem:///")
            _write_words(fs, "/dia/in.txt")
            g = JobGraph("diamond")
            g.node("gen", _count_conf("mem:///dia/in.txt",
                                      "mem:///dia/a", reduces=1))
            # fan-out: two consumers of gen's committed output...
            left = _count_conf("", "mem:///dia/left", reduces=1)
            left["mapred.input.format.class"] = \
                "tpumr.mapred.input_formats.SequenceFileInputFormat"
            del left["mapred.input.dir"]   # wired by the engine
            right = dict(left)
            right["mapred.output.dir"] = "mem:///dia/right"
            g.node("left", left)
            g.node("right", right)
            # ...and a fan-in joining both (comma-wired input dirs)
            join = _count_conf("", "mem:///dia/join", seq_out=False,
                               reduces=1)
            join["mapred.input.format.class"] = \
                "tpumr.mapred.input_formats.SequenceFileInputFormat"
            del join["mapred.input.dir"]
            g.node("join", join)
            g.edge("gen", "left").edge("gen", "right")
            g.edge("left", "join").edge("right", "join")

            client = PipelineClient(c.create_job_conf())
            running = client.submit(g)
            st = running.wait_for_completion(timeout=120)
            assert st["state"] == "SUCCEEDED", st
            assert all(n["state"] == "SUCCEEDED"
                       for n in st["nodes"].values()), st
            out = _read_parts(fs, "/dia/join")
            assert out, "join stage must produce output"
            # every stage ran exactly one job, wired in topo order
            jobs = {nid: n["job_id"] for nid, n in st["nodes"].items()}
            assert len(set(jobs.values())) == 4
            # stage jobs anchor at the pipeline's submit position
            m = c.master
            anchors = {m.jobs[j].sched_anchor for j in jobs.values()}
            assert len(anchors) == 1
            # the /pipeline surfaces serve it
            assert m.get_pipeline_status(
                running.pipeline_id)["state"] == "SUCCEEDED"
            assert any(p["pipeline_id"] == running.pipeline_id
                       for p in m.list_pipelines())

    def test_streamed_handoff_matches_dfs_chain(self):
        with MiniMRCluster(num_trackers=2, tpu_slots=0,
                           conf=_cluster_conf()) as c:
            fs = get_filesystem("mem:///")
            _write_words(fs, "/st/in.txt")

            # DFS-staged chain: count -> emit reads the committed
            # SequenceFiles back from DFS
            g1 = JobGraph("chain-dfs")
            g1.node("count", _count_conf("mem:///st/in.txt",
                                         "mem:///st/dfs-mid"))
            emit1 = _emit_conf("mem:///st/dfs-out")
            emit1["mapred.input.format.class"] = \
                "tpumr.mapred.input_formats.SequenceFileInputFormat"
            g1.node("emit", emit1)
            g1.edge("count", "emit")

            # streamed chain: same stages, stream edge — downstream
            # maps fetch the reduce partitions over the shuffle wire
            g2 = JobGraph("chain-stream")
            g2.node("count", _count_conf("mem:///st/in.txt",
                                         "mem:///st/str-mid"))
            g2.node("emit", _emit_conf("mem:///st/str-out"))
            g2.edge("count", "emit", stream=True)

            client = PipelineClient(c.create_job_conf())
            st1 = client.submit(g1).wait_for_completion(timeout=120)
            r2 = client.submit(g2)
            st2 = r2.wait_for_completion(timeout=120)
            assert st1["state"] == "SUCCEEDED", st1
            assert st2["state"] == "SUCCEEDED", st2

            out_dfs = _read_parts(fs, "/st/dfs-out")
            out_str = _read_parts(fs, "/st/str-out")
            assert out_dfs and out_str == out_dfs, \
                "streamed handoff must be byte-identical to the " \
                "DFS-staged chain"

            # the streamed stage actually streamed (its job counters
            # say so), and the upstream published handoff events
            m = c.master
            emit_job = st2["nodes"]["emit"]["job_id"]
            count_job = st2["nodes"]["count"]["job_id"]
            counters = m.jobs[emit_job].counters.to_dict()
            streamed = counters.get("Pipeline", {}).get(
                "HANDOFF_STREAMED_SPLITS", 0)
            assert streamed == 2, counters
            events = m.get_handoff_completion_events(count_job, 0)
            assert {e["map_index"] for e in events} == {0, 1}
            assert all(e["status"] == "SUCCEEDED" for e in events)
            # pipeline-scoped serving lifetime: with the pipeline over,
            # the purge oracle releases the copies and the trackers'
            # cleanup sweep drops the serving entries (they may already
            # be gone — the sweep races this assertion)
            assert m.handoff_purgeable(count_job) is True
            from tpumr.pipeline.handoff import serve_key
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                served = [k for t in c.trackers for k in t.map_outputs
                          if k[0] == serve_key(count_job)]
                if not served:
                    break
                time.sleep(0.1)
            assert not served, "handoff entries must purge once the " \
                               "pipeline is over"

    def test_kill_pipeline(self):
        with MiniMRCluster(num_trackers=1, tpu_slots=0,
                           conf=_cluster_conf()) as c:
            fs = get_filesystem("mem:///")
            _write_words(fs, "/kp/in.txt", lines=4000)
            g = JobGraph("killme")
            g.node("a", _count_conf("mem:///kp/in.txt", "mem:///kp/a"))
            emit = _emit_conf("mem:///kp/out")
            g.node("b", emit)
            g.edge("a", "b", stream=False)
            # make the dfs edge legal without seq input: b re-reads via
            # sequence input format
            emit["mapred.input.format.class"] = \
                "tpumr.mapred.input_formats.SequenceFileInputFormat"
            client = PipelineClient(c.create_job_conf())
            running = client.submit(g)
            assert running.kill() is True
            st = running.wait_for_completion(timeout=60)
            assert st["state"] == "KILLED"
            # every stage settles observably behind a dead pipeline —
            # nothing lingers PENDING/SUBMITTING/RUNNING forever
            assert all(n["state"] in ("SUCCEEDED", "FAILED", "SKIPPED")
                       for n in st["nodes"].values()), st

    def test_failed_stage_fails_pipeline_and_skips_downstream(self):
        with MiniMRCluster(num_trackers=1, tpu_slots=0,
                           conf=_cluster_conf()) as c:
            g = JobGraph("doomed")
            bad = _count_conf("mem:///nope/missing.txt", "mem:///no/a")
            g.node("a", bad)
            down = _emit_conf("mem:///no/out")
            down["mapred.input.format.class"] = \
                "tpumr.mapred.input_formats.SequenceFileInputFormat"
            g.node("b", down)
            g.edge("a", "b")
            client = PipelineClient(c.create_job_conf())
            running = client.submit(g)
            st = running.wait_for_completion(timeout=60)
            assert st["state"] == "FAILED"
            assert st["nodes"]["b"]["state"] == "SKIPPED"
            assert st["error"]


class TestTerasortPipeline:
    """The acceptance graph: teragen → sort → validate as ONE
    submission, the sort stage's partition sampling running master-side
    through its conf_hook, validate consuming the sort partitions over
    the streamed handoff — with byte-identical results vs the
    DFS-staged chain."""

    def teardown_method(self):
        FileSystem.clear_cache()

    @staticmethod
    def _graph(tag, rows_file, stream):
        g = JobGraph(f"terasort-{tag}")
        g.node("gen", {
            "mapred.input.dir": rows_file,
            "mapred.output.dir": f"mem:///ts/{tag}/gen",
            "mapred.input.format.class":
                "tpumr.mapred.input_formats.NLineInputFormat",
            "mapred.line.input.format.linespermap": 1,
            "mapred.mapper.class":
                "tpumr.examples.terasort.TeraGenMapper",
            "mapred.output.format.class":
                "tpumr.mapred.output_formats.SequenceFileOutputFormat",
            "mapred.reduce.tasks": 0,
        })
        g.node("sort", {
            "mapred.output.dir": f"mem:///ts/{tag}/sorted",
            "mapred.input.format.class":
                "tpumr.mapred.input_formats.SequenceFileInputFormat",
            "mapred.mapper.class":
                "tpumr.examples.terasort.TeraSortMapper",
            "mapred.reducer.class":
                "tpumr.mapred.api.IdentityReducer",
            "mapred.output.format.class":
                "tpumr.mapred.output_formats.SequenceFileOutputFormat",
            "mapred.output.key.comparator.class":
                "tpumr.mapred.api.RawComparator",
            "mapred.reduce.tasks": 2,
        }, conf_hook="tpumr.examples.terasort.pipeline_sort_hook")
        validate = {
            "mapred.output.dir": f"mem:///ts/{tag}/ok",
            "mapred.mapper.class":
                "tpumr.examples.terasort.TeraValidateMapper",
            "mapred.reducer.class":
                "tpumr.examples.terasort.TeraValidateReducer",
            "mapred.reduce.tasks": 1,
        }
        if not stream:
            validate["mapred.input.format.class"] = \
                "tpumr.mapred.input_formats.SequenceFileInputFormat"
            validate["mapred.min.split.size"] = 1 << 60
        g.node("validate", validate)
        g.edge("gen", "sort")
        g.edge("sort", "validate", stream=stream)
        return g

    def test_teragen_sort_validate_streamed_vs_dfs(self):
        with MiniMRCluster(num_trackers=2, tpu_slots=0,
                           conf=_cluster_conf()) as c:
            fs = get_filesystem("mem:///")
            # 400 rows over 2 teragen maps
            fs.write_bytes("/ts/rows.txt", b"0 200\n200 200\n")
            client = PipelineClient(c.create_job_conf())
            st_d = client.submit(self._graph(
                "dfs", "mem:///ts/rows.txt", False)) \
                .wait_for_completion(timeout=180)
            st_s = client.submit(self._graph(
                "str", "mem:///ts/rows.txt", True)) \
                .wait_for_completion(timeout=180)
            assert st_d["state"] == "SUCCEEDED", st_d
            assert st_s["state"] == "SUCCEEDED", st_s
            # the sorted artifacts agree record-for-record (SeqFile
            # BYTES embed a per-writer random sync marker, so records
            # are the identity that matters), and the validate stage's
            # TEXT output is byte-identical: empty = globally sorted,
            # in both chains
            def records(outdir):
                from tpumr.io import sequencefile
                out = []
                for st_ in sorted(fs.list_status(outdir),
                                  key=lambda s: str(s.path)):
                    if "part-" not in str(st_.path):
                        continue
                    f = fs.open(st_.path)
                    try:
                        length = fs.get_status(st_.path).length
                        out.append(list(sequencefile.Reader(f)
                                        .iter_range(0, length)))
                    finally:
                        f.close()
                return out

            sorted_d = records("/ts/dfs/sorted")
            sorted_s = records("/ts/str/sorted")
            assert sorted_d and sorted_s == sorted_d
            assert sum(len(p) for p in sorted_d) == 400
            ok_d = _read_parts(fs, "/ts/dfs/ok")
            ok_s = _read_parts(fs, "/ts/str/ok")
            assert ok_s == ok_d == b"", (ok_d, ok_s)
            # the streamed validate really streamed both partitions
            m = c.master
            val_job = st_s["nodes"]["validate"]["job_id"]
            counters = m.jobs[val_job].counters.to_dict()
            assert counters.get("Pipeline", {}).get(
                "HANDOFF_STREAMED_SPLITS", 0) == 2, counters


# ---------------------------------------------------------- loop nodes


def _kmeans_work(fs_dir, n=48, d=2, k=2):
    rng = np.random.default_rng(7)
    a = rng.normal(0.0, 0.1, size=(n // 2, d)).astype(np.float32)
    b = rng.normal(5.0, 0.1, size=(n // 2, d)).astype(np.float32)
    pts = np.concatenate([a, b])
    np.save(f"{fs_dir}/points.npy", pts)
    means = np.stack([a.mean(axis=0), b.mean(axis=0)])
    return pts, means


def _kmeans_loop_conf(work):
    return {
        "mapred.input.dir": f"file://{work}/points.npy",
        "mapred.output.dir": f"file://{work}/out-r{{round}}",
        "mapred.input.format.class":
            "tpumr.mapred.input_formats.DenseInputFormat",
        "tpumr.dense.split.rows": 16,
        "mapred.mapper.class": "tpumr.ops.kmeans.KMeansCpuMapper",
        "mapred.reducer.class":
            "tpumr.ops.kmeans.KMeansCentroidUpdateReducer",
        "mapred.reduce.tasks": 1,
        "tpumr.kmeans.centroids": f"file://{work}/cents-r{{round}}.npy",
        "tpumr.kmeans.centroids.out":
            f"file://{work}/cents-r{{next_round}}.npy",
    }


class TestLoopNodes:
    def teardown_method(self):
        FileSystem.clear_cache()
        from tpumr.ops.kmeans import clear_pipeline_caches
        clear_pipeline_caches()

    def test_convergence_settles_early(self, tmp_path):
        work = str(tmp_path)
        _pts, means = _kmeans_work(work)
        # start AT the cluster means: round 0's shift is ~0 — the
        # predicate settles the loop after ONE round, far below the
        # cutoff
        np.save(f"{work}/cents-r0.npy", means.astype(np.float32))
        with MiniMRCluster(num_trackers=1, tpu_slots=0,
                           conf=_cluster_conf()) as c:
            g = JobGraph("kmeans")
            g.loop("km", _kmeans_loop_conf(work), max_rounds=5,
                   converge={"group": "KMeans",
                             "counter": "CENTROID_SHIFT_MILLI",
                             "op": "le", "value": 5})
            client = PipelineClient(c.create_job_conf())
            st = client.submit(g).wait_for_completion(timeout=120)
            assert st["state"] == "SUCCEEDED", st
            assert st["nodes"]["km"]["rounds_run"] == 1
            got = np.load(f"{work}/cents-r1.npy")
            assert np.allclose(got, means, atol=1e-3)

    def test_max_rounds_cutoff_and_versioned_centroids(self, tmp_path):
        work = str(tmp_path)
        _pts, means = _kmeans_work(work)
        # start far off AND demand impossible convergence (< 0): the
        # loop must stop at the max-rounds cutoff exactly
        np.save(f"{work}/cents-r0.npy",
                np.array([[10.0, 10.0], [-10.0, -10.0]], np.float32))
        with MiniMRCluster(num_trackers=1, tpu_slots=0,
                           conf=_cluster_conf()) as c:
            g = JobGraph("kmeans-cutoff")
            g.loop("km", _kmeans_loop_conf(work), max_rounds=3,
                   converge={"group": "KMeans",
                             "counter": "CENTROID_SHIFT_MILLI",
                             "op": "lt", "value": 0})
            client = PipelineClient(c.create_job_conf())
            st = client.submit(g).wait_for_completion(timeout=180)
            assert st["state"] == "SUCCEEDED", st
            assert st["nodes"]["km"]["rounds_run"] == 3
            # every round versioned its centroid file — nothing was
            # rewritten under a live cache key (the devcache staleness
            # fix: no per-round clear_centroid_cache needed)
            import os
            for r in range(4):
                assert os.path.exists(f"{work}/cents-r{r}.npy")
            got = np.load(f"{work}/cents-r3.npy")
            assert np.allclose(np.sort(got, axis=0),
                               np.sort(means, axis=0), atol=0.2)
