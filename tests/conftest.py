"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective paths are
validated on 8 virtual CPU devices (the driver separately dry-run-compiles
the multi-chip path via __graft_entry__.dryrun_multichip). Must run before
any jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# the axon TPU plugin overrides JAX_PLATFORMS at import; the config update
# after import reliably pins tests to the virtual 8-device CPU mesh
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from tpumr.fs.filesystem import FileSystem  # noqa: E402


@pytest.fixture(autouse=True)
def _clear_fs_cache():
    """Each test gets fresh FileSystem instances (mem: FS is stateful)."""
    FileSystem.clear_cache()
    yield
    FileSystem.clear_cache()
