"""Process-isolated task execution ≈ the reference's child-JVM tier.

Covers the TaskRunner/JvmManager/Child/TaskController contracts
(reference: mapred/Child.java:69, JvmManager.java:322-413,
TaskController.java): with ``tpumr.task.isolation=process`` every CPU
attempt is a real OS process, so a crashing (os._exit) or runaway-memory
mapper costs one attempt — the tracker survives and the job completes on
retry. The last test launches children through the native setuid
task-controller as an unprivileged user (root-only, ≈ TestPipesAsDifferentUser).
"""

import os
import subprocess
import sys
import time

import pytest

from tpumr.fs import get_filesystem
from tpumr.mapred.job_client import JobClient
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.mini_cluster import MiniMRCluster


class PidWordCountMapper:
    """Wordcount that also records which pid ran it."""

    def configure(self, conf):
        pass

    def map(self, key, value, output, reporter):
        reporter.incr_counter("pids", f"pid_{os.getpid()}")
        for w in value.split():
            output.collect(w, 1)

    def close(self):
        pass


class SumReducer:
    def configure(self, conf):
        pass

    def reduce(self, key, values, output, reporter):
        output.collect(key, sum(values))

    def close(self):
        pass


class CrashOnFirstAttemptMapper:
    """os._exit on attempt 0 — in-process this would take down the whole
    tracker (and this pytest process); isolated it costs one attempt."""

    def configure(self, conf):
        self.attempt = conf.get("tpumr.task.attempt.id", "")

    def map(self, key, value, output, reporter):
        if self.attempt.endswith("_0"):
            os._exit(66)
        output.collect(value, 1)

    def close(self):
        pass


class MemoryBombOnFirstAttemptMapper:
    """Allocates far past the task memory limit on attempt 0 and then
    lingers so the TaskMemoryManager sampler catches and kills it."""

    def configure(self, conf):
        self.attempt = conf.get("tpumr.task.attempt.id", "")

    def map(self, key, value, output, reporter):
        if self.attempt.endswith("_0"):
            hog = [bytearray(16 * 1024 * 1024) for _ in range(24)]  # 384 MB
            time.sleep(30)
            del hog
        output.collect(value, 1)

    def close(self):
        pass


@pytest.fixture(scope="module")
def cluster():
    conf = JobConf()
    conf.set("tpumr.task.isolation", "process")
    conf.set("mapred.map.max.attempts", 3)
    with MiniMRCluster(num_trackers=2, conf=conf, cpu_slots=2,
                       tpu_slots=0) as c:
        yield c


def _job_conf(cluster, tmp_path, name):
    conf = cluster.create_job_conf()
    conf.set_job_name(name)
    conf.set("tpumr.task.isolation", "process")
    src = tmp_path / f"{name}-in.txt"
    src.write_bytes(b"alpha beta\nbeta gamma\n" * 50)
    conf.set_input_paths(f"file://{src}")
    conf.set_output_path(f"file://{tmp_path}/{name}-out")
    conf.set("mapred.min.split.size", 1)
    conf.set("mapred.map.tasks", 2)
    return conf


def _read_output(out_dir):
    fs = get_filesystem(f"file://{out_dir}")
    out = {}
    for st in fs.list_files(f"file://{out_dir}"):
        if st.path.name.startswith("part-"):
            for line in fs.read_bytes(st.path).decode().splitlines():
                k, v = line.split("\t")
                out[k] = int(v)
    return out


def test_isolated_wordcount_runs_out_of_process(cluster, tmp_path):
    conf = _job_conf(cluster, tmp_path, "iso-wc")
    conf.set_class("mapred.mapper.class", PidWordCountMapper)
    conf.set_class("mapred.reducer.class", SumReducer)
    conf.set_num_reduce_tasks(1)

    result = JobClient(conf).run_job(conf)
    assert result.successful
    assert _read_output(tmp_path / "iso-wc-out") == {
        "alpha": 50, "beta": 100, "gamma": 50}
    # the proof of isolation: no map ran inside this (tracker) process
    pid_counters = result.counters.to_dict().get("pids", {})
    assert pid_counters, "mapper pid counters missing"
    assert f"pid_{os.getpid()}" not in pid_counters


def test_crashing_mapper_fails_attempt_tracker_survives(cluster, tmp_path):
    """VERDICT r1 'done' criterion: a crashing mapper fails its attempt,
    the tracker survives, and the job completes via retry."""
    conf = _job_conf(cluster, tmp_path, "iso-crash")
    conf.set_class("mapred.mapper.class", CrashOnFirstAttemptMapper)
    conf.set_num_reduce_tasks(1)

    result = JobClient(conf).run_job(conf)
    assert result.successful
    # both trackers still heartbeat: a fresh job schedules and finishes
    conf2 = _job_conf(cluster, tmp_path, "iso-after-crash")
    conf2.set_class("mapred.mapper.class", PidWordCountMapper)
    conf2.set_class("mapred.reducer.class", SumReducer)
    assert JobClient(conf2).run_job(conf2).successful


def test_memory_bomb_killed_and_retried(cluster, tmp_path):
    from tpumr.mapred.node_health import GLOBAL_MEMORY_MANAGER
    conf = _job_conf(cluster, tmp_path, "iso-mem")
    conf.set_class("mapred.mapper.class", MemoryBombOnFirstAttemptMapper)
    conf.set_num_reduce_tasks(1)
    # child baseline RSS in this image is ~165 MB (interpreter);
    # the limit sits above that, the bomb far above the limit
    conf.set("mapred.task.limit.maxrss.mb", 320)

    before = len(GLOBAL_MEMORY_MANAGER.killed)
    result = JobClient(conf).run_job(conf)
    assert result.successful
    assert len(GLOBAL_MEMORY_MANAGER.killed) > before, \
        "memory manager never killed the bombing attempt"


# --------------------------------------------------------------------------
# launch through the setuid task-controller as an unprivileged user

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TASKCTL = os.path.join(REPO, "native", "task-controller")

UIDMAP_MODULE = '''\
import os

class UidMapper:
    def configure(self, conf):
        pass

    def map(self, key, value, output, reporter):
        reporter.incr_counter("ids", "uid_%d" % os.getuid())

    def close(self):
        pass
'''


@pytest.fixture(scope="module")
def tc_sandbox(tmp_path_factory):
    """Sandbox the task-controller policy allows, traversable by the
    dropped-privilege child, with a world-readable copy of tpumr (the repo
    itself lives under /root, unreadable to the task user)."""
    import shutil

    scratch = tmp_path_factory.mktemp("tciso")
    sandbox = scratch / "local"
    sandbox.mkdir()
    pylib = scratch / "pylib"
    shutil.copytree(os.path.join(REPO, "tpumr"), pylib / "tpumr")
    (pylib / "uidmap.py").write_text(UIDMAP_MODULE)
    for root, dirs, files in os.walk(scratch):
        os.chmod(root, 0o755)
        for f in files:
            os.chmod(os.path.join(root, f), 0o644)
    # pytest tmp parents are 0700: open traversal up to the tmp root
    import tempfile
    stop = {tempfile.gettempdir(), "/"}
    p = scratch
    while str(p) not in stop and str(p.parent) != str(p):
        try:
            os.chmod(p, 0o755)
        except OSError:
            break
        p = p.parent

    conf = scratch / "task-controller.cfg"
    conf.write_text("min.user.id=100\nbanned.users=root,daemon\n"
                    f"allowed.local.dirs={sandbox}\n")
    os.chmod(conf, 0o600)
    binary = scratch / "task-controller"
    r = subprocess.run(
        ["cc", "-O2", "-Wall", f"-DTC_CONF_PATH=\"{conf}\"",
         "-o", str(binary), "task-controller.c"],
        cwd=TASKCTL, capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    os.chmod(binary, 0o755)
    return {"sandbox": sandbox, "pylib": pylib, "binary": binary,
            "scratch": scratch}


@pytest.mark.skipif(os.getuid() != 0, reason="needs root to drop to nobody")
def test_launch_through_task_controller_as_nobody(tc_sandbox):
    """End-to-end: tracker (root) launches the child through the native
    task-controller, which drops to 'nobody' before exec — the uid counter
    reported over the umbilical proves both the launch path and the
    privilege drop (reference: LinuxTaskController + TestPipesAsDifferentUser)."""
    import pwd
    try:
        pwd.getpwnam("nobody")
    except KeyError:
        pytest.skip("no 'nobody' user")

    # the child resolves tpumr from the world-readable copy
    sys.path.insert(0, str(tc_sandbox["pylib"]))
    try:
        conf = JobConf()
        conf.set("tpumr.task.isolation", "process")
        conf.set("mapred.task.tracker.task-controller",
                 str(tc_sandbox["binary"]))
        conf.set("tpumr.task.user", "nobody")
        conf.set("mapred.local.dir", str(tc_sandbox["sandbox"]))
        with MiniMRCluster(num_trackers=1, conf=conf, cpu_slots=1,
                           tpu_slots=0) as cluster:
            src = tc_sandbox["scratch"] / "in.txt"
            src.write_bytes(b"x\ny\n")
            os.chmod(src, 0o644)
            jconf = cluster.create_job_conf()
            jconf.set_job_name("tc-uid")
            jconf.set("tpumr.task.isolation", "process")
            jconf.set_input_paths(f"file://{src}")
            jconf.set("mapred.mapper.class", "uidmap.UidMapper")
            from tpumr.mapred.output_formats import NullOutputFormat
            jconf.set_class("mapred.output.format.class", NullOutputFormat)
            jconf.set_num_reduce_tasks(0)
            result = JobClient(jconf).run_job(jconf)
        assert result.successful
        ids = result.counters.to_dict().get("ids", {})
        nobody_uid = pwd.getpwnam("nobody").pw_uid
        assert f"uid_{nobody_uid}" in ids, f"uid counters: {ids}"
        assert "uid_0" not in ids, "child ran as root"
    finally:
        sys.path.remove(str(tc_sandbox["pylib"]))


def test_child_logs_retained_and_served(cluster, tmp_path):
    """≈ userlogs + TaskLogServlet: a child's stdout/stderr survives job
    cleanup in the userlogs tree and is listed/served by the tracker."""
    conf = _job_conf(cluster, tmp_path, "iso-logs")
    conf.set_class("mapred.mapper.class", ChattyMapper)
    conf.set_num_reduce_tasks(0)
    result = JobClient(conf).run_job(conf)
    assert result.successful

    # the umbilical reports success before the tracker's monitor thread
    # finishes reaping the child and copying its log — poll briefly
    deadline = time.time() + 10
    found = None
    while time.time() < deadline and found is None:
        for t in cluster.trackers:
            for aid in t.list_task_logs():
                if "hello from the child" in t.get_task_log(aid):
                    found = (t, aid)
        time.sleep(0.1)
    assert found, "this job's child log never appeared in userlogs"
    with pytest.raises(KeyError):
        found[0].get_task_log("attempt_0_0000_m_000099_0")

    # symlink defense: the attempt dir is task-user-owned in setuid mode —
    # a child.log swapped for a symlink must NOT be followed by the
    # (possibly root-running) tracker when serving /tasklog
    tracker, aid = found
    import os
    from tpumr.mapred.ids import TaskAttemptID
    job_id = str(TaskAttemptID.parse(aid).task.job)
    log = os.path.join(tracker.local_root, "userlogs", job_id, aid,
                       "child.log")
    secret = tmp_path / "secret.txt"
    secret.write_text("root-only contents")
    os.remove(log)
    os.symlink(str(secret), log)
    with pytest.raises(KeyError):
        tracker.get_task_log(aid)

    # malformed / hostile ids keep the KeyError contract (no parser
    # exceptions escape, no path bytes survive)
    for bad in ("garbage", "attempt_0_x_m_000000_0",
                "attempt_../x_0000_m_000000_0", ""):
        with pytest.raises(KeyError):
            tracker.get_task_log(bad)


def test_userlog_purge_skips_jobs_with_running_attempts(cluster, tmp_path):
    """A live attempt's userlogs dir must survive retention purge even
    when the job dir's mtime is ancient (appends don't bump dir mtime)."""
    import os
    tracker = cluster.trackers[0]
    logs = os.path.join(tracker.local_root, "userlogs")
    live_dir = os.path.join(logs, "job_live_0001")
    dead_dir = os.path.join(logs, "job_dead_0001")
    os.makedirs(live_dir)
    os.makedirs(dead_dir)
    old = time.time() - 48 * 3600
    os.utime(live_dir, (old, old))
    os.utime(dead_dir, (old, old))
    from tpumr.mapred.ids import TaskAttemptID
    from tpumr.mapred.task import TaskStatus
    with tracker.lock:
        tracker.running["attempt_live_0001_m_000000_0"] = TaskStatus(
            TaskAttemptID.parse("attempt_live_0001_m_000000_0"))
    try:
        tracker._purge_old_userlogs()
    finally:
        with tracker.lock:
            tracker.running.pop("attempt_live_0001_m_000000_0")
    assert os.path.isdir(live_dir), "live job's userlogs were purged"
    assert not os.path.isdir(dead_dir), "retention purge stopped working"


class ChattyMapper:
    def configure(self, conf):
        pass

    def map(self, key, value, output, reporter):
        print("hello from the child", flush=True)
        output.collect(value, 1)

    def close(self):
        pass


class SecretProbeMapper:
    """Reports whether the cluster secret is visible in the CHILD's conf."""

    def configure(self, conf):
        self._visible = 1 if conf.get("tpumr.rpc.secret") else 0

    def map(self, key, value, output, reporter):
        output.collect("secret_visible", self._visible)

    def close(self):
        pass


def test_strip_cluster_secret_from_child_conf(tmp_path):
    """tpumr.task.strip.cluster.secret=true: the child process's job conf
    carries no secret-bearing keys (it still authenticates via its job
    token); default keeps them (tdfs-reading tasks need the secret)."""
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.job_client import JobClient
    from tpumr.mapred.mini_cluster import MiniMRCluster

    src = tmp_path / "in.txt"
    src.write_bytes(b"x\n")
    results = {}
    for strip in (True, False):
        conf = JobConf()
        conf.set("tpumr.rpc.secret", "probe-secret")
        conf.set("tpumr.task.isolation", "process")
        with MiniMRCluster(num_trackers=1, cpu_slots=1, tpu_slots=0,
                           conf=conf) as c:
            jc = c.create_job_conf()
            jc.set("tpumr.task.isolation", "process")
            jc.set("tpumr.task.strip.cluster.secret", strip)
            jc.set_input_paths(f"file://{src}")
            jc.set_output_path(f"file://{tmp_path}/out-{strip}")
            jc.set_class("mapred.mapper.class", SecretProbeMapper)
            jc.set_num_reduce_tasks(0)
            assert JobClient(jc).run_job(jc).successful
        text = (tmp_path / f"out-{strip}" / "part-00000").read_text()
        results[strip] = text.strip()
    assert results[True] == "secret_visible\t0"
    assert results[False] == "secret_visible\t1"
