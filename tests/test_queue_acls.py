"""Queue administration ACLs ≈ QueueManager.java + mapred-queue-acls.xml:
per-queue submit/administer ACLs enforced at submit and kill."""

import pytest

from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.jobtracker import JobMaster
from tpumr.mapred.queue_manager import AccessControlList, QueueManager
from tpumr.security import UserGroupInformation, server_side_ugi


def ugi(user, groups=()):
    return UserGroupInformation(user, list(groups))


class TestAccessControlList:
    def test_star_allows_everyone(self):
        acl = AccessControlList("*")
        assert acl.allows(ugi("anyone"))

    def test_users_and_groups(self):
        acl = AccessControlList("alice,bob devs,ops")
        assert acl.allows(ugi("alice"))
        assert acl.allows(ugi("carol", ["ops"]))
        assert not acl.allows(ugi("carol", ["qa"]))

    def test_blank_allows_no_one(self):
        acl = AccessControlList("")
        assert not acl.allows(ugi("alice"))

    def test_users_only_spec(self):
        acl = AccessControlList("alice")
        assert acl.allows(ugi("alice")) and not acl.allows(ugi("bob"))

    def test_groups_only_spec_leading_blank(self):
        # the reference's groups-only form: leading space, then groups
        # (AccessControlList.java split(" ", 2) — parts[0] is empty)
        acl = AccessControlList(" devs,ops")
        assert acl.allows(ugi("carol", ["devs"]))
        assert acl.allows(ugi("dan", ["ops"]))
        # a USER literally named like the group must NOT pass
        assert not acl.allows(ugi("devs"))
        assert not acl.allows(ugi("erin", ["qa"]))


class TestQueueManager:
    def make(self, **kv):
        conf = JobConf()
        for k, v in kv.items():
            conf.set(k, v)
        return QueueManager(conf)

    def test_acls_disabled_is_open(self):
        qm = self.make(**{"mapred.queue.names": "q1",
                          "mapred.queue.q1.acl-submit-job": ""})
        qm.check_submit("q1", ugi("anyone"))  # acls off: no exception

    def test_submit_allowed_and_denied(self):
        qm = self.make(**{"mapred.acls.enabled": True,
                          "mapred.queue.names": "prod,adhoc",
                          "mapred.queue.prod.acl-submit-job": "alice devs"})
        qm.check_submit("prod", ugi("alice"))
        qm.check_submit("prod", ugi("dave", ["devs"]))
        qm.check_submit("adhoc", ugi("bob"))     # unset ACL = open
        with pytest.raises(PermissionError, match="cannot submit"):
            qm.check_submit("prod", ugi("bob"))

    def test_undefined_queue_rejected_when_names_configured(self):
        qm = self.make(**{"mapred.queue.names": "prod"})
        with pytest.raises(PermissionError, match="not defined"):
            qm.check_submit("nosuch", ugi("alice"))

    def test_capacity_phantom_semantics_kept_without_explicit_names(self):
        # no mapred.queue.names, ACLs OFF: capacity's unconfigured-queue
        # bucket must keep working (scheduled last, never rejected)
        qm = self.make(**{"tpumr.capacity.queues": "prod,adhoc"})
        qm.check_submit("experimental", ugi("alice"))

    def test_acls_on_always_validates_queue_existence(self):
        # with mapred.acls.enabled=true the queue must exist even when
        # mapred.queue.names was never set — otherwise every phantom
        # queue defaults to an open "*" ACL and enforcement is hollow
        # (the reference's QueueManager.java always validates)
        qm = self.make(**{"mapred.acls.enabled": True,
                          "tpumr.capacity.queues": "prod,adhoc"})
        qm.check_submit("prod", ugi("alice"))
        with pytest.raises(PermissionError, match="not defined"):
            qm.check_submit("experimental", ugi("alice"))

    def test_administer_owner_and_admins(self):
        qm = self.make(**{
            "mapred.acls.enabled": True,
            "mapred.queue.names": "prod",
            "mapred.queue.prod.acl-administer-jobs": "opsuser",
            "mapred.cluster.administrators": "root"})
        qm.check_administer("prod", ugi("owner1"), owner="owner1")
        qm.check_administer("prod", ugi("opsuser"), owner="owner1")
        qm.check_administer("prod", ugi("root"), owner="owner1")
        with pytest.raises(PermissionError, match="cannot administer"):
            qm.check_administer("prod", ugi("mallory"), owner="owner1")


class TestServerSideGroups:
    def test_static_conf_mapping(self):
        conf = JobConf()
        conf.set("tpumr.user.groups.erin", "devs, ops")
        u = server_side_ugi("erin", conf)
        assert u.groups == ["devs", "ops"]

    def test_empty_user_falls_back_to_process_identity(self):
        assert server_side_ugi("", JobConf()).user


class TestMasterEnforcement:
    @pytest.fixture()
    def master(self):
        conf = JobConf()
        conf.set("mapred.acls.enabled", True)
        conf.set("mapred.queue.names", "default,prod")
        conf.set("mapred.queue.prod.acl-submit-job", "alice")
        conf.set("mapred.queue.prod.acl-administer-jobs", "opsuser")
        m = JobMaster(conf).start()
        yield m
        m.stop()

    def submit(self, master, user, queue="prod"):
        return master.submit_job(
            {"mapred.job.queue.name": queue, "user.name": user,
             "mapred.reduce.tasks": 0}, [{"locations": []}])

    def test_submit_acl_enforced(self, master):
        jid = self.submit(master, "alice")
        assert jid in master.list_jobs()
        with pytest.raises(PermissionError, match="cannot submit"):
            self.submit(master, "bob")
        with pytest.raises(PermissionError, match="not defined"):
            self.submit(master, "alice", queue="nosuch")

    def test_identityless_submit_is_anonymous_not_daemon(self):
        # an identity-less submit must never inherit the daemon's own
        # process identity — even when that identity is a cluster
        # administrator (which would bypass every queue submit ACL)
        import getpass
        conf = JobConf()
        conf.set("mapred.acls.enabled", True)
        conf.set("mapred.queue.names", "prod")
        conf.set("mapred.queue.prod.acl-submit-job", "alice")
        conf.set("mapred.cluster.administrators", getpass.getuser())
        m = JobMaster(conf).start()
        try:
            with pytest.raises(PermissionError, match="cannot submit"):
                m.submit_job({"mapred.job.queue.name": "prod",
                              "mapred.reduce.tasks": 0},
                             [{"locations": []}])
        finally:
            m.stop()

    def test_job_level_view_and_modify_acls(self):
        """≈ JobACLsManager: with ACLs on, mapreduce.job.acl-view-job /
        acl-modify-job grant per-job access beyond owner/queue-admin;
        unlisted users are denied VIEW (the reference's closed default),
        and a job-modify grantee may kill without queue rights."""
        from tpumr.ipc.rpc import RpcClient, RpcError
        from tpumr.security.tokens import derive_user_key
        from tpumr.security import UserGroupInformation
        secret = b"acl-test-secret"
        conf = JobConf()
        conf.set("tpumr.rpc.secret", secret.decode())
        conf.set("mapred.acls.enabled", True)
        conf.set("mapred.queue.names", "prod")
        conf.set("mapred.queue.prod.acl-submit-job", "*")
        conf.set("mapred.queue.prod.acl-administer-jobs", " ops")
        m = JobMaster(conf).start()
        try:
            host, port = m.address

            def client(user):
                key = derive_user_key(secret, user)
                return RpcClient(host, port, secret=key,
                                 scope=f"user:{user}")

            with UserGroupInformation("alice", []).do_as():
                jid = client("alice").call(
                    "submit_job",
                    {"mapred.job.queue.name": "prod",
                     "user.name": "alice", "mapred.reduce.tasks": 0,
                     "mapreduce.job.acl-view-job": "viewer",
                     "mapreduce.job.acl-modify-job": "killer"},
                    [{"locations": []}])
            # owner views; the view-ACL grantee views; a stranger can't
            assert client("alice").call("get_job_status", jid)
            assert client("viewer").call("get_job_status", jid)
            assert client("viewer").call("get_counters", jid) is not None
            with pytest.raises(RpcError, match="cannot view"):
                client("mallory").call("get_job_status", jid)
            with pytest.raises(RpcError, match="cannot view"):
                client("mallory").call("get_job_conf", jid)
            # view does not grant modify; the modify grantee may kill
            with pytest.raises(RpcError, match="cannot administer"):
                client("viewer").call("kill_job", jid, "viewer")
            # the infrastructure tier (cluster-secret daemons: trackers
            # localizing confs, proxying events) is NOT view-gated —
            # locking queue ACLs down must never break the trackers
            daemon = RpcClient(host, port, secret=secret)
            assert daemon.call("get_job_conf", jid)
            assert daemon.call("get_job_status", jid)
            assert client("killer").call("kill_job", jid, "killer") \
                is True
        finally:
            m.stop()

    def test_kill_acl_enforced(self, master):
        jid = self.submit(master, "alice")
        with pytest.raises(PermissionError, match="cannot administer"):
            master.kill_job(jid, user="mallory")
        # a caller sending NO identity is anonymous — never the daemon's
        # own (administrator) identity, so the old 1-arg signature can't
        # bypass the ACL
        with pytest.raises(PermissionError, match="cannot administer"):
            master.kill_job(jid)
        assert master.get_job_status(jid)["state"] != "KILLED"
        # queue admin may kill
        master.kill_job(jid, user="opsuser")
        # owner may kill their own (fresh job)
        jid2 = self.submit(master, "alice")
        master.kill_job(jid2, user="alice")
