"""mapred.lib.db tier (tpumr/mapred/lib_db.py ≈ DBInputFormat /
DBOutputFormat / DBConfiguration): LIMIT/OFFSET splitting, DB-API
plumbing, and a full MR job from one sqlite table into another."""

import sqlite3

import pytest

from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.lib_db import (DBInputFormat, DBOutputFormat, DBSplit,
                                 db_connect)
from tpumr.mapred.split import InputSplit


@pytest.fixture()
def db(tmp_path):
    path = tmp_path / "store.db"
    conn = sqlite3.connect(path)
    conn.execute("CREATE TABLE clicks (id INTEGER, page TEXT, n INTEGER)")
    rows = [(i, f"page{i % 3}", i % 7) for i in range(100)]
    conn.executemany("INSERT INTO clicks VALUES (?, ?, ?)", rows)
    conn.execute("CREATE TABLE totals (page TEXT, total INTEGER)")
    conn.commit()
    conn.close()
    return path


def _conf(db, **kw):
    conf = JobConf()
    conf.set("tpumr.db.connect", str(db))
    conf.set("tpumr.db.input.table", "clicks")
    conf.set("tpumr.db.input.order.by", "id")
    for k, v in kw.items():
        conf.set(k, v)
    return conf


class TestSplitsAndReader:
    def test_splits_partition_the_ordered_table(self, db):
        conf = _conf(db)
        fmt = DBInputFormat()
        splits = fmt.get_splits(conf, 4)
        assert [s.row_count for s in splits] == [25, 25, 25, 25]
        seen = []
        for s in splits:
            for idx, row in fmt.get_record_reader(s, conf):
                assert idx == row[0]        # ordered by id
                seen.append(row[0])
        assert seen == list(range(100))     # no overlap, no gaps

    def test_split_wire_roundtrip(self):
        s = DBSplit(25, 50)
        back = InputSplit.from_dict(s.to_dict())
        assert isinstance(back, DBSplit)
        assert (back.start, back.row_count) == (25, 50)
        assert back.length == 50

    def test_unordered_multisplit_refused(self, db):
        conf = _conf(db)
        conf.unset("tpumr.db.input.order.by")
        with pytest.raises(ValueError, match="UNORDERED"):
            DBInputFormat().get_splits(conf, 4)
        # one split is always safe
        assert len(DBInputFormat().get_splits(conf, 1)) == 1

    def test_custom_query_and_fields(self, db):
        conf = _conf(db, **{
            "tpumr.db.input.query":
                "SELECT page, n FROM clicks WHERE n > 5 ORDER BY id"})
        fmt = DBInputFormat()
        splits = fmt.get_splits(conf, 2)
        rows = [r for s in splits
                for _, r in fmt.get_record_reader(s, conf)]
        assert rows and all(r[1] > 5 for r in rows)

    def test_bad_identifier_is_loud(self, db):
        conf = _conf(db)
        conf.set("tpumr.db.input.table", "clicks; DROP TABLE clicks")
        with pytest.raises(ValueError, match="identifier"):
            DBInputFormat().get_splits(conf, 1)


class Sum:                       # reducer: totals per page
    def configure(self, conf):
        pass

    def reduce(self, key, values, output, reporter):
        output.collect(key, sum(values))

    def close(self):
        pass


class PageMapper:
    def configure(self, conf):
        pass

    def map(self, key, row, output, reporter):
        _id, page, n = row
        output.collect(page, n)

    def close(self):
        pass


class TestEndToEndJob:
    def test_sqlite_to_sqlite_mr_job(self, db, tmp_path):
        """The reference's lib.db promise end-to-end: map over a TABLE,
        reduce, INSERT the aggregates into another table."""
        from tpumr.mapred.local_runner import run_job
        conf = _conf(db)
        conf.set_job_name("db2db")
        conf.set("mapred.input.format.class",
                 "tpumr.mapred.lib_db.DBInputFormat")
        conf.set("mapred.output.format.class",
                 "tpumr.mapred.lib_db.DBOutputFormat")
        conf.set("tpumr.db.output.table", "totals")
        conf.set("tpumr.db.output.fields", "page,total")
        conf.set("mapred.map.tasks", 4)
        conf.set_class("mapred.mapper.class", PageMapper)
        conf.set_class("mapred.reducer.class", Sum)
        conf.set_num_reduce_tasks(1)
        # FileOutputCommitter wants an output dir for its temp tree even
        # though the real output goes through the DB connection
        conf.set_output_path(f"file://{tmp_path}/scratch")
        result = run_job(conf)
        assert result.successful, result.error
        conn = sqlite3.connect(db)
        got = dict(conn.execute("SELECT page, total FROM totals"))
        conn.close()
        expect = {}
        for i in range(100):
            expect[f"page{i % 3}"] = expect.get(f"page{i % 3}", 0) + i % 7
        assert got == expect

    def test_output_specs_fail_fast(self, db):
        conf = _conf(db)
        conf.set("tpumr.db.output.table", "missing_table")
        with pytest.raises(Exception, match="missing_table|no such"):
            DBOutputFormat().check_output_specs(conf)


def test_db_connect_requires_target():
    with pytest.raises(ValueError, match="db.connect"):
        db_connect(JobConf())


class FailingReducer:
    def configure(self, conf):
        pass

    def reduce(self, key, values, output, reporter):
        output.collect(key, sum(values))
        raise RuntimeError("boom after emitting")

    def close(self):
        pass


class TestReviewRegressions:
    def test_failed_task_commits_nothing(self, db, tmp_path):
        """A reducer that raises after buffering rows must not leave
        partial INSERTs behind (the abort seam — file outputs get this
        from the committer; direct-write formats need it explicitly)."""
        from tpumr.mapred.local_runner import run_job
        conf = _conf(db)
        conf.set("mapred.input.format.class",
                 "tpumr.mapred.lib_db.DBInputFormat")
        conf.set("mapred.output.format.class",
                 "tpumr.mapred.lib_db.DBOutputFormat")
        conf.set("tpumr.db.output.table", "totals")
        conf.set("tpumr.db.output.fields", "page,total")
        conf.set_class("mapred.mapper.class", PageMapper)
        conf.set_class("mapred.reducer.class", FailingReducer)
        conf.set_num_reduce_tasks(1)
        conf.set_output_path(f"file://{tmp_path}/scratch")
        with pytest.raises(Exception, match="boom"):
            run_job(conf)
        conn = sqlite3.connect(db)
        assert conn.execute("SELECT COUNT(*) FROM totals""").fetchone()[0] == 0
        conn.close()

    def test_order_by_direction_and_compound(self, db):
        conf = _conf(db)
        conf.set("tpumr.db.input.order.by", "id DESC")
        fmt = DBInputFormat()
        rows = [r for s in fmt.get_splits(conf, 2)
                for _, r in fmt.get_record_reader(s, conf)]
        assert [r[0] for r in rows] == list(range(99, -1, -1))
        conf.set("tpumr.db.input.order.by", "page, id")
        assert len(fmt.get_splits(conf, 3)) == 3
        conf.set("tpumr.db.input.order.by", "id; DROP TABLE clicks")
        with pytest.raises(ValueError):
            fmt.get_splits(conf, 2)

    def test_row_width_validated_at_write(self, db):
        from tpumr.mapred.lib_db import _DBRecordWriter
        conf = _conf(db)
        w = _DBRecordWriter(conf, "totals", ["page", "total"])
        with pytest.raises(ValueError, match="row width"):
            w.write(("a", 1, 2), None)
        w.abort()

    def test_reader_closes_on_early_abandon(self, db):
        conf = _conf(db)
        fmt = DBInputFormat()
        (split,) = fmt.get_splits(conf, 1)
        reader = fmt.get_record_reader(split, conf)
        it = iter(reader)
        next(it)
        it.close()                      # abandon mid-iteration
        # the underlying connection is closed -> cursor use raises
        with pytest.raises(Exception):
            reader.cursor.fetchone()
