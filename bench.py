"""Benchmark: FULL-JOB wall-clock on the BASELINE.md workloads.

Every number is an end-to-end job through LocalJobRunner — splits → map →
(shuffle) → reduce → commit — never a bare-kernel microbenchmark. The
north star (BASELINE.json): K-Means on 100M points, TPU vs CPU-only
MapReduce, ≥5×.

Modes measured for K-Means:
- ``tpu cold``  — first job: storage read + host→device staging + XLA
  compile all included.
- ``tpu warm``  — subsequent jobs of the iterative driver (HBM split cache
  resident, compile cached): the steady state of the actual workload
  (Shirahata's K-Means runs tens of rounds; round 0 amortizes away).
  Reported as mean over 3 rounds with min/max so round-to-round variance
  is visible, not hidden.
- ``cpu batch`` — the framework's OWN vectorized CPU backend
  (CpuBatchMapRunner + numpy): the strongest honest CPU-only baseline.
- ``cpu per-record`` — the reference's execution model (one record per
  map() call ≈ the pipes socket loop), measured as a full job on 1M
  points (100M would take ~1h); reported as a rate, used only as a
  secondary comparison.

Also measured: wordcount, pi, and terasort (host shuffle vs device
shuffle) at real sizes — the BASELINE.md workload table.

Output contract: ONE JSON line on stdout
  {"metric", "value", "unit", "vs_baseline"}
vs_baseline = cpu-batch job wall-clock / tpu WARM job wall-clock (the
iterative steady state). The cold ratio and every other row go to stderr
and to ``bench_details.json``.

Scale: env BENCH_SCALE=small shrinks every workload ~50× for smoke runs;
default is the full size (100M-point K-Means needs ~13 GB RAM + disk).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np


def log(*a: object) -> None:
    print(*a, file=sys.stderr, flush=True)


SMALL = os.environ.get("BENCH_SCALE") == "small"


def _fs(path: str):
    from tpumr.fs import get_filesystem
    return get_filesystem(path)


# --------------------------------------------------------------- K-Means


def kmeans_conf(work: str, mode: str, rows_per_split: int):
    from tpumr.mapred.input_formats import DenseInputFormat
    from tpumr.mapred.jobconf import JobConf

    conf = JobConf()
    conf.set_job_name(f"bench-kmeans-{mode}")
    conf.set_input_paths(f"file://{work}/points.npy")
    conf.set_output_path(f"file://{work}/out-{mode}-{time.time_ns()}")
    conf.set_input_format(DenseInputFormat)
    conf.set("tpumr.dense.split.rows", rows_per_split)
    conf.set("tpumr.kmeans.centroids", f"file://{work}/cents.npy")
    conf.set("mapred.reducer.class", "tpumr.examples.basic.CentroidReducer")
    conf.set_num_reduce_tasks(1)
    conf.set("tpumr.tpu.split.cache.mb", 14_000)  # whole dataset resident
    conf.set_map_kernel("kmeans-assign")
    conf.set("mapred.mapper.class", "tpumr.ops.kmeans.KMeansCpuMapper")
    if mode == "tpu":
        conf.set("tpumr.local.run.on.tpu", True)
    elif mode == "cpu-record":
        conf.set("tpumr.cpu.batch.map", False)   # reference execution model
    return conf


def run_kmeans_job(work: str, mode: str, rows_per_split: int) -> float:
    from tpumr.mapred.local_runner import run_job
    from tpumr.ops.kmeans import clear_centroid_cache

    clear_centroid_cache()
    conf = kmeans_conf(work, mode, rows_per_split)
    t0 = time.time()
    result = run_job(conf)
    dt = time.time() - t0
    assert result.successful, f"kmeans {mode} job failed: {result.error}"
    return dt


def bench_kmeans(rows: dict) -> tuple[float, float]:
    n = 2_000_000 if SMALL else 100_000_000
    n_record = min(n, 200_000 if SMALL else 1_000_000)
    d, k = 16, 16
    per_split = 4_000_000 if not SMALL else 500_000

    work = tempfile.mkdtemp(prefix="tpumr-bench-kmeans-")
    log(f"[kmeans] generating {n:,} x {d} points ({n * d * 4 / 1e9:.1f} GB) "
        f"in {work} ...")
    rng = np.random.default_rng(0)
    cents = rng.normal(size=(k, d)).astype(np.float32)
    np.save(os.path.join(work, "cents.npy"), cents)
    # chunked generation+write keeps peak RAM ~1 split
    out = open(os.path.join(work, "points.npy"), "wb")
    header = np.lib.format.header_data_from_array_1_0(
        np.empty((0, d), np.float32))
    header["shape"] = (n, d)
    np.lib.format.write_array_header_1_0(out, header)
    chunk = 4_000_000
    for lo in range(0, n, chunk):
        m = min(chunk, n - lo)
        out.write(rng.normal(size=(m, d)).astype(np.float32).tobytes())
    out.close()

    t_cpu = run_kmeans_job(work, "cpu", per_split)
    log(f"[kmeans] cpu-batch full job ({n:,} pts): {t_cpu:.2f}s "
        f"({n / t_cpu / 1e6:.2f}M rec/s)")
    rows["kmeans_cpu_batch_job_s"] = round(t_cpu, 3)
    rows["kmeans_cpu_batch_rec_per_s"] = round(n / t_cpu)

    t_cold = run_kmeans_job(work, "tpu", per_split)
    log(f"[kmeans] tpu COLD full job (read+stage+compile): {t_cold:.2f}s")
    rows["kmeans_tpu_cold_job_s"] = round(t_cold, 3)

    warm = [run_kmeans_job(work, "tpu", per_split) for _ in range(3)]
    t_warm = sum(warm) / len(warm)
    log(f"[kmeans] tpu WARM full jobs: mean {t_warm:.2f}s "
        f"(min {min(warm):.2f} max {max(warm):.2f}) — variance is host-side "
        f"job machinery (split planning, reduce, commit), the device work "
        f"is microseconds at this size")
    rows["kmeans_tpu_warm_job_s"] = round(t_warm, 3)
    rows["kmeans_tpu_warm_job_min_s"] = round(min(warm), 3)
    rows["kmeans_tpu_warm_job_max_s"] = round(max(warm), 3)
    rows["kmeans_tpu_warm_rec_per_s"] = round(n / t_warm)

    # reference execution model (per-record map calls) on a small full job
    sub = os.path.join(work, "sub")
    os.makedirs(sub, exist_ok=True)
    pts = np.lib.format.open_memmap(os.path.join(work, "points.npy"),
                                    mode="r")
    np.save(os.path.join(sub, "points.npy"),
            np.ascontiguousarray(pts[:n_record]))
    np.save(os.path.join(sub, "cents.npy"), cents)
    t_rec = run_kmeans_job(sub, "cpu-record", n_record)
    log(f"[kmeans] cpu PER-RECORD full job ({n_record:,} pts): {t_rec:.2f}s "
        f"({n_record / t_rec / 1e3:.1f}k rec/s — the reference's "
        f"one-record-per-map()-call model)")
    rows["kmeans_cpu_per_record_rec_per_s"] = round(n_record / t_rec)
    rows["kmeans_n_points"] = n
    return t_cpu, t_warm


# ------------------------------------------------------------- wordcount


def bench_wordcount(rows: dict) -> None:
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.local_runner import run_job

    mb = 4 if SMALL else 200
    work = tempfile.mkdtemp(prefix="tpumr-bench-wc-")
    words = [f"word{i:04d}".encode() for i in range(4096)]
    rng = np.random.default_rng(1)
    path = os.path.join(work, "text.txt")
    with open(path, "wb") as f:
        line = b" ".join(words[i] for i in rng.integers(0, 4096, 12)) + b"\n"
        reps = mb * 1024 * 1024 // len(line)
        idx = rng.integers(0, 4096, size=(reps, 12))
        f.write(b"\n".join(b" ".join(words[j] for j in r) for r in idx))
    size = os.path.getsize(path)

    conf = JobConf()
    conf.set_job_name("bench-wordcount")
    conf.set_input_paths(f"file://{path}")
    conf.set_output_path(f"file://{work}/out")
    from tpumr.mapred.input_formats import RawTextInputFormat
    conf.set_input_format(RawTextInputFormat)
    conf.set_map_kernel("wordcount")
    conf.set("mapred.reducer.class", "tpumr.examples.basic.LongSumReducer")
    conf.set("mapred.combiner.class", "tpumr.examples.basic.LongSumReducer")
    conf.set_num_reduce_tasks(1)
    t0 = time.time()
    result = run_job(conf)
    dt = time.time() - t0
    assert result.successful
    log(f"[wordcount] {size / 1e6:.0f} MB full job (vectorized batch "
        f"tokenize): {dt:.2f}s ({size / dt / 1e6:.0f} MB/s)")
    rows["wordcount_job_s"] = round(dt, 3)
    rows["wordcount_mb_per_s"] = round(size / dt / 1e6, 1)


# -------------------------------------------------------------------- pi


def bench_pi(rows: dict) -> None:
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.local_runner import run_job

    samples = 10_000_000 if SMALL else 400_000_000
    maps = 8
    work = tempfile.mkdtemp(prefix="tpumr-bench-pi-")
    path = os.path.join(work, "seeds.txt")
    with open(path, "w") as f:
        for m in range(maps):
            f.write(f"{m} {samples // maps}\n")

    def run(mode: str) -> float:
        from tpumr.mapred.input_formats import NLineInputFormat
        conf = JobConf()
        conf.set_job_name(f"bench-pi-{mode}")
        conf.set_input_paths(f"file://{path}")
        conf.set_output_path(f"file://{work}/out-{mode}-{time.time_ns()}")
        conf.set_input_format(NLineInputFormat)
        conf.set("mapred.line.input.format.linespermap", 1)
        conf.set_map_kernel("pi-sampler")
        conf.set("mapred.reducer.class",
                 "tpumr.examples.basic.LongSumReducer")
        conf.set_num_reduce_tasks(1)
        if mode == "tpu":
            conf.set("tpumr.local.run.on.tpu", True)
        t0 = time.time()
        assert run_job(conf).successful
        return time.time() - t0

    t_tpu = run("tpu")
    t_tpu_warm = run("tpu")  # compile cached
    t_cpu = run("cpu")
    log(f"[pi] {samples:,} samples: tpu {t_tpu:.2f}s (warm "
        f"{t_tpu_warm:.2f}s), cpu-batch {t_cpu:.2f}s -> "
        f"{t_cpu / t_tpu_warm:.1f}x")
    rows["pi_tpu_job_s"] = round(t_tpu_warm, 3)
    rows["pi_cpu_batch_job_s"] = round(t_cpu, 3)
    rows["pi_samples"] = samples


# ---------------------------------------------------------------- matmul


def bench_matmul(rows: dict) -> None:
    """Blocked C = A @ B as a map-only job (BASELINE workload 4): each
    map owns a row block of A, B rides as a side file, C blocks leave
    through SequenceFile outputs."""
    from tpumr.mapred.input_formats import DenseInputFormat
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.local_runner import run_job
    from tpumr.mapred.output_formats import SequenceFileOutputFormat
    from tpumr.ops.matmul import clear_b_cache

    n = 1024 if SMALL else 4096
    work = tempfile.mkdtemp(prefix="tpumr-bench-mm-")
    rng = np.random.default_rng(2)
    np.save(os.path.join(work, "a.npy"),
            rng.normal(size=(n, n)).astype(np.float32))
    np.save(os.path.join(work, "b.npy"),
            rng.normal(size=(n, n)).astype(np.float32))

    def run(mode: str) -> float:
        clear_b_cache()
        conf = JobConf()
        conf.set_job_name(f"bench-matmul-{mode}")
        conf.set_input_paths(f"file://{work}/a.npy")
        conf.set_output_path(f"file://{work}/out-{mode}-{time.time_ns()}")
        conf.set_input_format(DenseInputFormat)
        conf.set("tpumr.dense.split.rows", n // 4)
        conf.set("tpumr.matmul.b", f"file://{work}/b.npy")
        conf.set_map_kernel("matmul-block")
        conf.set_output_format(SequenceFileOutputFormat)
        conf.set_num_reduce_tasks(0)
        if mode == "tpu":
            conf.set("tpumr.local.run.on.tpu", True)
        t0 = time.time()
        assert run_job(conf).successful
        return time.time() - t0

    t_tpu_cold = run("tpu")
    t_tpu = run("tpu")        # compile cached
    t_cpu = run("cpu")
    flops = 2 * n ** 3
    log(f"[matmul] {n}x{n} @ {n}x{n} full job: tpu {t_tpu:.2f}s warm "
        f"({flops / t_tpu / 1e12:.2f} TFLOP/s incl. job machinery, cold "
        f"{t_tpu_cold:.2f}s), cpu-batch {t_cpu:.2f}s -> "
        f"{t_cpu / t_tpu:.1f}x")
    rows["matmul_n"] = n
    rows["matmul_tpu_job_s"] = round(t_tpu, 3)
    rows["matmul_tpu_cold_job_s"] = round(t_tpu_cold, 3)
    rows["matmul_cpu_batch_job_s"] = round(t_cpu, 3)


# -------------------------------------------------------------- terasort


def bench_terasort(rows: dict) -> None:
    from tpumr.examples.terasort import make_terasort_conf
    from tpumr.mapred.local_runner import run_job

    n = 100_000 if SMALL else 2_000_000
    work = tempfile.mkdtemp(prefix="tpumr-bench-ts-")
    from tpumr.cli import main as cli_main
    t0 = time.time()
    assert cli_main(["examples", "teragen", str(n),
                     f"file://{work}/gen", "-m", "4"]) == 0
    log(f"[terasort] teragen {n:,} records: {time.time() - t0:.2f}s")

    def run(device: bool) -> float:
        mode = "device" if device else "host"
        conf = make_terasort_conf(f"file://{work}/gen",
                                  f"file://{work}/out-{mode}-"
                                  f"{time.time_ns()}", 4,
                                  device_shuffle=device)
        t0 = time.time()
        assert run_job(conf).successful
        return time.time() - t0

    t_host = run(False)
    t_dev_cold = run(True)    # pays the dest/exchange/sort XLA compiles
    t_dev = run(True)         # compile cache warm: the steady state
    log(f"[terasort] {n:,} records ({n * 100 / 1e6:.0f} MB): host shuffle "
        f"{t_host:.2f}s, device shuffle cold {t_dev_cold:.2f}s / warm "
        f"{t_dev:.2f}s -> warm {t_host / t_dev:.2f}x")
    rows["terasort_host_job_s"] = round(t_host, 3)
    rows["terasort_device_cold_job_s"] = round(t_dev_cold, 3)
    rows["terasort_device_job_s"] = round(t_dev, 3)
    rows["terasort_records"] = n

    # A FRESH process with the persistent compilation cache populated by
    # the runs above (TPUMR_JAX_CACHE_DIR, set per bench run in main):
    # the production cold path — every new worker process inherits the
    # compile bill already paid, so "cold" stops meaning minutes of XLA.
    prog = (
        "import sys, time\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})\n"
        "from tpumr.examples.terasort import make_terasort_conf\n"
        "from tpumr.mapred.local_runner import run_job\n"
        f"conf = make_terasort_conf('file://{work}/gen',\n"
        f"    'file://{work}/out-fresh', 4, device_shuffle=True)\n"
        "t0 = time.time()\n"
        "assert run_job(conf).successful\n"
        "print('FRESH_DEVICE_JOB_S', time.time() - t0)\n")
    import subprocess
    import sys as _sys
    out = subprocess.run([_sys.executable, "-c", prog],
                         capture_output=True, text=True, timeout=1800)
    if out.returncode == 0:
        t_fresh = float(out.stdout.split("FRESH_DEVICE_JOB_S")[1].strip())
        log(f"[terasort] fresh-process device job with inherited "
            f"compilation cache: {t_fresh:.2f}s (in-process true cold was "
            f"{t_dev_cold:.2f}s)")
        rows["terasort_device_fresh_process_cached_s"] = round(t_fresh, 3)
    else:
        log(f"[terasort] fresh-process cached run FAILED: "
            f"{out.stderr.strip()[-400:]}")
        rows["terasort_device_fresh_process_cached_s"] = \
            f"failed: rc={out.returncode}"


# ---------------------------------------------------------------- hybrid


def bench_hybrid(rows: dict) -> None:
    """The heart of the reference, measured end-to-end: the profiling
    hybrid scheduler (Shirahata) runs each job's maps on BOTH pools,
    measures per-backend mean runtimes, and skews placement by the
    acceleration factor. On this harness kmeans (compute-heavy, tiny
    map outputs) measures accel >> 1 and lands mostly on the TPU pool;
    blocked matmul ships its full N^2 output back over the tunnel
    (bandwidth-bound), measures accel < 1, and the CPU pool carries it —
    the hybrid premise working in both directions."""
    from tpumr.core.counters import BackendCounter
    from tpumr.mapred.input_formats import DenseInputFormat
    from tpumr.mapred.job_client import JobClient
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.mini_cluster import MiniMRCluster
    from tpumr.mapred.output_formats import SequenceFileOutputFormat
    from tpumr.ops.kmeans import clear_centroid_cache
    from tpumr.ops.matmul import clear_b_cache

    work = tempfile.mkdtemp(prefix="tpumr-bench-hybrid-")
    rng = np.random.default_rng(4)
    # split sizes MATCH the earlier kmeans/matmul workloads so their XLA
    # compiles are reused — the per-backend means then measure steady-
    # state task runtimes, not one first-task compile (the reference's
    # mean-over-all-attempts profiling has the same cold-start skew)
    n_km, d, k = (2_000_000 if SMALL else 32_000_000), 16, 16
    np.save(os.path.join(work, "cents.npy"),
            rng.normal(size=(k, d)).astype(np.float32))
    out = open(os.path.join(work, "points.npy"), "wb")
    header = np.lib.format.header_data_from_array_1_0(
        np.empty((0, d), np.float32))
    header["shape"] = (n_km, d)
    np.lib.format.write_array_header_1_0(out, header)
    for lo in range(0, n_km, 2_000_000):
        m = min(2_000_000, n_km - lo)
        out.write(rng.normal(size=(m, d)).astype(np.float32).tobytes())
    out.close()
    n_mm = 1024 if SMALL else 4096
    np.save(os.path.join(work, "a.npy"),
            rng.normal(size=(n_mm, n_mm)).astype(np.float32))
    np.save(os.path.join(work, "b.npy"),
            rng.normal(size=(n_mm, n_mm)).astype(np.float32))

    def run_and_profile(c, conf, tag, out_suffix=""):
        clear_centroid_cache()
        clear_b_cache()
        if out_suffix:
            conf.set_output_path(conf.get("mapred.output.dir") + out_suffix)
        t0 = time.time()
        result = JobClient(conf).run_job(conf)
        dt = time.time() - t0
        assert result.successful, f"hybrid {tag} failed: {result.error}"
        jip = c.master.jobs.get(str(result.job_id))
        accel = jip.acceleration_factor() if jip is not None else 0.0
        tpu = result.counters.value(BackendCounter.GROUP,
                                    BackendCounter.TPU_MAP_TASKS)
        cpu = result.counters.value(BackendCounter.GROUP,
                                    BackendCounter.CPU_MAP_TASKS)
        # placement trace in assignment order (TaskReport stamping,
        # ≈ JobTracker.java:3414-3433): the convergence signature is the
        # all-TPU TAIL once the starvation rule / minimizer kicks in
        tail = 0
        seq = ""
        if jip is not None:
            placements = sorted(
                ((t.report.start_time or 0.0, bool(t.report.run_on_tpu))
                 for t in jip.maps), key=lambda p: p[0])
            seq = "".join("T" if p[1] else "c" for p in placements)
            for b in reversed(seq):
                if b != "T":
                    break
                tail += 1
        log(f"[hybrid] {tag}: accel factor {accel:.2f}, placement "
            f"tpu={tpu} cpu={cpu}, assignment order {seq}, "
            f"all-TPU tail {tail}, job {dt:.2f}s")
        rows[f"hybrid_{tag}_accel"] = round(accel, 3)
        rows[f"hybrid_{tag}_tpu_maps"] = tpu
        rows[f"hybrid_{tag}_cpu_maps"] = cpu
        rows[f"hybrid_{tag}_placement_seq"] = seq
        rows[f"hybrid_{tag}_tpu_tail"] = tail

    # The reference authors' exact single-node config: ONE tracker with
    # 3 CPU + 1 TPU map slots (conf/mapred-site.xml:23-33), optional
    # scheduling on. With 8 maps of 4M rows the first wave fills the 4
    # slots; by the time they finish both backends have profiles, the
    # warm accel factor is >> 1, pending (4) < accel x 1 x 1 — and the
    # tail of the job converges to the TPU pool.
    base = JobConf()
    base.set("mapred.jobtracker.map.optionalscheduling", True)
    with MiniMRCluster(num_trackers=1, cpu_slots=3, tpu_slots=1,
                       conf=base) as c:
        conf = c.create_job_conf()
        conf.set_job_name("hybrid-kmeans")
        conf.set_input_paths(f"file://{work}/points.npy")
        conf.set_output_path(f"file://{work}/out-km")
        conf.set_input_format(DenseInputFormat)
        # Twice as many maps as the tracker has slots: the starvation
        # rule can only fire while maps are still PENDING, so the job
        # must outlast the first assignment wave (round-2 BENCH_r02
        # structurally couldn't converge — every map was assigned before
        # any profile existed). 4M-row splits keep per-task device
        # compute large enough that the warm accel factor clears 1 by a
        # wide margin (tiny splits drown in per-task tunnel roundtrips).
        conf.set("tpumr.dense.split.rows", 4_000_000 if not SMALL
                 else 250_000)
        conf.set("tpumr.kmeans.centroids", f"file://{work}/cents.npy")
        conf.set_map_kernel("kmeans-assign")
        conf.set("mapred.reducer.class",
                 "tpumr.examples.basic.CentroidReducer")
        conf.set_num_reduce_tasks(1)
        # round 1 pays cold staging per TPU task (a single-pass job is
        # upload-bound on a tunneled chip); round 2 of the ITERATIVE
        # workload hits the HBM split cache, the measured accel factor
        # flips above 1, and optional scheduling STARVES the CPU pool
        # mid-job once pending < accel x tpuCapacity x trackers
        # (JobQueueTaskScheduler.java:290-327) — the convergence clause:
        # the assignment tail goes all-TPU
        run_and_profile(c, conf, "kmeans_round1")
        run_and_profile(c, conf, "kmeans_round2", out_suffix="-r2")
        # round 3 under the implemented f(x,y) minimizer
        # (JobQueueTaskScheduler.java:181-219 as mode=minimize): with
        # t_cpu >> t_tpu the optimum puts (nearly) everything on the
        # accelerator — the majority-TPU placement row
        conf.set("tpumr.scheduler.mode", "minimize")
        run_and_profile(c, conf, "kmeans_minimize", out_suffix="-r3")
        conf.set("tpumr.scheduler.mode", "shirahata")

        conf = c.create_job_conf()
        conf.set_job_name("hybrid-matmul")
        conf.set_input_paths(f"file://{work}/a.npy")
        conf.set_output_path(f"file://{work}/out-mm")
        conf.set_input_format(DenseInputFormat)
        conf.set("tpumr.dense.split.rows", n_mm // 4)
        conf.set("tpumr.matmul.b", f"file://{work}/b.npy")
        conf.set_map_kernel("matmul-block")
        conf.set_output_format(SequenceFileOutputFormat)
        conf.set_num_reduce_tasks(0)
        run_and_profile(c, conf, "matmul")


# ------------------------------------------------------------------ main


def main() -> None:
    # fresh per-run persistent compilation cache: in-process "cold" rows
    # stay TRUE cold (empty cache), while the fresh-subprocess terasort
    # row below measures the production cold path (inherited cache)
    os.environ["TPUMR_JAX_CACHE_DIR"] = tempfile.mkdtemp(
        prefix="tpumr-bench-jaxcache-")
    import jax
    log(f"backend={jax.default_backend()} devices={jax.devices()} "
        f"scale={'small' if SMALL else 'full'}")

    rows: dict = {}
    t_cpu, t_warm = bench_kmeans(rows)
    for fn in (bench_wordcount, bench_pi, bench_matmul, bench_terasort,
               bench_hybrid):
        # workloads run in ONE process here; in production each job owns
        # its runner. Drop the previous workload's HBM split cache so a
        # 6.4 GB resident K-Means dataset doesn't starve the terasort
        # device buffers into allocation thrash.
        from tpumr.mapred.tpu_runner import clear_split_caches
        clear_split_caches()
        try:
            fn(rows)
        except Exception as e:  # noqa: BLE001 — secondary rows best-effort
            log(f"[{fn.__name__}] FAILED: {type(e).__name__}: {e}")
            rows[fn.__name__] = f"failed: {e}"

    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "bench_details.json"), "w") as f:
        json.dump(rows, f, indent=2, sort_keys=True)
    log(f"detail rows -> bench_details.json: "
        f"{json.dumps(rows, sort_keys=True)}")

    n = rows["kmeans_n_points"]
    print(json.dumps({
        "metric": f"kmeans {n / 1e6:.0f}M-pt full-job wall-clock, warm "
                  f"iterative round (tpu kernel vs vectorized cpu-only "
                  f"batch baseline; cold={rows['kmeans_tpu_cold_job_s']}s)",
        "value": round(t_warm, 3),
        "unit": "seconds/job",
        "vs_baseline": round(t_cpu / t_warm, 2),
    }))


if __name__ == "__main__":
    main()
