"""Benchmark: K-Means map-task throughput, TPU kernel path vs CPU-only path.

Measures the BASELINE.json primary metric — map-task records/sec/chip on the
K-Means assignment workload — through the REAL task path (run_map_task:
input format → runner selection → kernel/mapper → MapOutputBuffer), not a
bare kernel microbenchmark:

- TPU path: DenseSplit staged into HBM (split cache warm, as in every
  round ≥ 2 of an iterative job), Pallas/XLA assignment + partial sums.
- CPU baseline: the same task through the per-record CPU mapper — the
  reference's execution model (one record at a time through the map call,
  ≈ the pipes socket loop) on a sample, extrapolated per record.

Prints ONE JSON line:
  {"metric": ..., "value": records/sec/chip, "unit": ..., "vs_baseline": x}
vs_baseline = TPU rate / CPU-only rate (north star: ≥5, BASELINE.md).
Diagnostics go to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np


def log(*a: object) -> None:
    print(*a, file=sys.stderr, flush=True)


def run_map(conf, split, on_tpu: bool, attempt: int, work: str):
    from tpumr.mapred.api import Reporter
    from tpumr.mapred.ids import JobID, TaskAttemptID, TaskID
    from tpumr.mapred.map_task import run_map_task
    from tpumr.mapred.task import Task

    aid = TaskAttemptID(TaskID(JobID("bench", 1), True, 0), attempt)
    task = Task(aid, partition=0, num_reduces=1, split=split.to_dict(),
                run_on_tpu=on_tpu, tpu_device_id=0 if on_tpu else -1)
    t0 = time.time()
    run_map_task(conf, task, os.path.join(work, f"a{attempt}"), Reporter())
    return time.time() - t0


def main() -> None:
    import jax

    from tpumr.mapred.input_formats import DenseInputFormat
    from tpumr.mapred.jobconf import JobConf
    from tpumr.ops import kmeans  # noqa: F401 — registers kernels

    n, d, k = 1_000_000, 16, 16
    cpu_sample = 20_000
    log(f"backend={jax.default_backend()} devices={jax.devices()}")
    rng = np.random.default_rng(0)
    points = rng.normal(size=(n, d)).astype(np.float32)
    cents = rng.normal(size=(k, d)).astype(np.float32)

    work = tempfile.mkdtemp(prefix="tpumr-bench-")
    np.save(os.path.join(work, "points.npy"), points)
    np.save(os.path.join(work, "cents.npy"), cents)

    conf = JobConf()
    conf.set_input_paths(f"file://{work}/points.npy")
    conf.set("tpumr.kmeans.centroids", f"file://{work}/cents.npy")
    conf.set("tpumr.map.kernel", "kmeans-assign")
    conf.set("mapred.mapper.class", "tpumr.ops.kmeans.KMeansCpuMapper")
    conf.set_input_format(DenseInputFormat)
    conf.set("tpumr.dense.split.rows", n)

    fmt = DenseInputFormat()
    [tpu_split] = fmt.get_splits(conf, 1)

    # ---- TPU path: round 0 pays staging+compile; measure warm rounds
    t_cold = run_map(conf, tpu_split, True, 0, work)
    log(f"tpu round0 (stage+compile): {t_cold:.2f}s")
    times = []
    for it in range(1, 4):
        dt = run_map(conf, tpu_split, True, it, work)
        times.append(dt)
        log(f"tpu round{it} (HBM-resident): {dt:.3f}s")
    tpu_rate = n / (sum(times) / len(times))

    # ---- CPU-only baseline: per-record mapper on a sample
    conf_cpu = JobConf(conf)
    conf_cpu.set("tpumr.dense.split.rows", cpu_sample)
    cpu_split = fmt.get_splits(conf_cpu, 1)[0]
    t_cpu = run_map(conf_cpu, cpu_split, False, 9, work)
    cpu_rate = cpu_sample / t_cpu
    log(f"cpu sample ({cpu_sample} rec): {t_cpu:.2f}s -> {cpu_rate:,.0f} rec/s")
    log(f"tpu warm: {tpu_rate:,.0f} rec/s/chip -> {tpu_rate / cpu_rate:.1f}x cpu")

    print(json.dumps({
        "metric": "kmeans map-task throughput (1M pts x16d, 16 clusters, "
                  "warm HBM split cache)",
        "value": round(tpu_rate, 1),
        "unit": "records/sec/chip",
        "vs_baseline": round(tpu_rate / cpu_rate, 2),
    }))


if __name__ == "__main__":
    main()
