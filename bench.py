"""Benchmark: FULL-JOB wall-clock on the BASELINE.md workloads.

Every number is an end-to-end job through LocalJobRunner — splits → map →
(shuffle) → reduce → commit — never a bare-kernel microbenchmark. The
north star (BASELINE.json): K-Means on 100M points, TPU vs CPU-only
MapReduce, ≥5×.

Modes measured for K-Means:
- ``tpu cold``  — first job: storage read + host→device staging + XLA
  compile all included.
- ``tpu warm``  — subsequent jobs of the iterative driver (HBM split cache
  resident, compile cached): the steady state of the actual workload
  (Shirahata's K-Means runs tens of rounds; round 0 amortizes away).
  Reported as mean over 3 rounds with min/max so round-to-round variance
  is visible, not hidden.
- ``cpu batch`` — the framework's OWN vectorized CPU backend
  (CpuBatchMapRunner + numpy): the strongest honest CPU-only baseline.
- ``cpu per-record`` — the reference's execution model (one record per
  map() call ≈ the pipes socket loop), measured as a full job on 1M
  points (100M would take ~1h); reported as a rate, used only as a
  secondary comparison.

Also measured: wordcount, pi, and terasort (host shuffle vs device
shuffle) at real sizes — the BASELINE.md workload table.

Output contract: ONE JSON line on stdout
  {"metric", "value", "unit", "vs_baseline"}
vs_baseline = cpu-batch job wall-clock / tpu WARM job wall-clock (the
iterative steady state). The cold ratio and every other row go to stderr
and to ``bench_details.json``.

Scale: env BENCH_SCALE=small shrinks every workload ~50× for smoke runs;
default is the full size (100M-point K-Means needs ~13 GB RAM + disk).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np


def log(*a: object) -> None:
    print(*a, file=sys.stderr, flush=True)


SMALL = os.environ.get("BENCH_SCALE") == "small"

#: set by probe_backend() in main(); device workloads are skipped (host
#: rows still captured) when the accelerator backend can't initialize —
#: a wedged tunnel must yield a diagnosable partial artifact, not rc=1.
TPU_OK = True

#: re-assert JAX_PLATFORMS via config.update in every subprocess: on this
#: image the env var alone is NOT honored at import, so an operator's cpu
#: pin would silently not pin. Load-bearing platform knowledge — keep it
#: in one place.
_PIN_PREAMBLE = ("import os\n"
                 "_p = os.environ.get('JAX_PLATFORMS')\n"
                 "if _p:\n"
                 "    import jax\n"
                 "    jax.config.update('jax_platforms', _p)\n")


def probe_backend(rows: dict,
                  attempts: int = max(1, int(os.environ.get(
                      "BENCH_PROBE_ATTEMPTS", 2))),
                  timeout_s: float = float(os.environ.get(
                      "BENCH_PROBE_TIMEOUT", 240.0))) -> bool:
    """Pre-flight: initialize the default JAX backend in a SUBPROCESS so
    a wedged device tunnel can neither hang this process nor poison its
    (not-yet-initialized) backend state. Bounded retry; on failure a
    structured record lands in the artifact and the caller pins this
    process to the CPU backend for host-only rows."""
    prog = (_PIN_PREAMBLE +
            "import jax, json\n"
            "d = jax.devices()\n"
            "print('PROBE_OK', json.dumps({'backend': jax.default_backend(),"
            " 'n': len(d), 'kind': d[0].device_kind}))")
    failures: list[dict] = []
    # cpu counts as *requested* only when it leads the platform list —
    # "tpu,cpu" is jax's fallback-order syntax, and a fallback to cpu
    # there is still a device failure we must flag
    cpu_requested = os.environ.get("JAX_PLATFORMS", "") \
        .split(",")[0].strip().lower() == "cpu"
    for i in range(attempts):
        t0 = time.time()
        # own process group: on timeout we killpg, so a wedged child's
        # pipe-holding descendants can't park communicate() forever
        child = subprocess.Popen([sys.executable, "-c", prog],
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.PIPE, text=True,
                                 start_new_session=True)
        try:
            stdout, stderr = child.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            import signal
            try:
                os.killpg(child.pid, signal.SIGKILL)
            except OSError:
                child.kill()
            try:  # bounded reap — never wait on a D-state child forever
                child.communicate(timeout=10)
            except Exception:  # noqa: BLE001
                pass
            failures.append({"attempt": i, "elapsed_s": round(
                time.time() - t0, 1), "error": f"backend init exceeded "
                f"{timeout_s:.0f}s (wedged tunnel?)"})
            # no retry after a timeout: the kill we just delivered to a
            # mid-init device process is exactly what wedges the tunnel
            # for hours on this platform — a second 240s attempt is
            # guaranteed dead air against a now-wedged backend
            break
        ok_line = next((ln for ln in stdout.splitlines()
                        if ln.startswith("PROBE_OK")), None)
        if child.returncode == 0 and ok_line:
            info = json.loads(ok_line.split(" ", 1)[1])
            info["probe_s"] = round(time.time() - t0, 1)
            rows["backend_probe"] = info
            if info["backend"] == "cpu" and not cpu_requested:
                # jax fell back to CPU silently: device rows would be
                # CPU numbers wearing tpu labels — the exact misleading
                # artifact this probe exists to prevent
                rows["tpu_unavailable"] = True
                info["error"] = "jax silently fell back to cpu backend"
                log("[probe] backend initialized as CPU FALLBACK — "
                    "treating device as unavailable, host-only rows")
                return False
            log(f"[probe] backend {info['backend']} "
                f"({info['kind']} x{info['n']}) in {info['probe_s']}s")
            return True
        failures.append({"attempt": i, "rc": child.returncode,
                         "error": stderr.strip()[-400:]})
        if i < attempts - 1:
            time.sleep(5)
    rows["tpu_unavailable"] = True
    rows["backend_probe"] = {"failures": failures}
    last = failures[-1].get("error", "?") if failures else "?"
    log(f"[probe] backend UNAVAILABLE after {len(failures)} attempts: "
        f"{last[:200]} — capturing host-only rows")
    return False


def _fs(path: str):
    from tpumr.fs import get_filesystem
    return get_filesystem(path)


# --------------------------------------------------------------- K-Means


def kmeans_conf(work: str, mode: str, rows_per_split: int):
    from tpumr.mapred.input_formats import DenseInputFormat
    from tpumr.mapred.jobconf import JobConf

    conf = JobConf()
    conf.set_job_name(f"bench-kmeans-{mode}")
    conf.set_input_paths(f"file://{work}/points.npy")
    conf.set_output_path(f"file://{work}/out-{mode}-{time.time_ns()}")
    conf.set_input_format(DenseInputFormat)
    conf.set("tpumr.dense.split.rows", rows_per_split)
    conf.set("tpumr.kmeans.centroids", f"file://{work}/cents.npy")
    conf.set("mapred.reducer.class", "tpumr.examples.basic.CentroidReducer")
    conf.set_num_reduce_tasks(1)
    conf.set("tpumr.tpu.split.cache.mb", 14_000)  # whole dataset resident
    conf.set_map_kernel("kmeans-assign")
    conf.set("mapred.mapper.class", "tpumr.ops.kmeans.KMeansCpuMapper")
    if mode == "tpu":
        conf.set("tpumr.local.run.on.tpu", True)
    elif mode == "cpu-record":
        conf.set("tpumr.cpu.batch.map", False)   # reference execution model
    return conf


def run_kmeans_job(work: str, mode: str, rows_per_split: int) -> float:
    from tpumr.mapred.local_runner import run_job
    from tpumr.ops.kmeans import clear_centroid_cache

    clear_centroid_cache()
    conf = kmeans_conf(work, mode, rows_per_split)
    t0 = time.time()
    result = run_job(conf)
    dt = time.time() - t0
    assert result.successful, f"kmeans {mode} job failed: {result.error}"
    return dt


def bench_kmeans(rows: dict) -> tuple[float, float]:
    n = 2_000_000 if SMALL else 100_000_000
    n_record = min(n, 200_000 if SMALL else 1_000_000)
    d, k = 16, 16
    per_split = 4_000_000 if not SMALL else 500_000

    work = tempfile.mkdtemp(prefix="tpumr-bench-kmeans-")
    log(f"[kmeans] generating {n:,} x {d} points ({n * d * 4 / 1e9:.1f} GB) "
        f"in {work} ...")
    rng = np.random.default_rng(0)
    cents = rng.standard_normal(size=(k, d), dtype=np.float32)
    np.save(os.path.join(work, "cents.npy"), cents)
    # chunked generation+write keeps peak RAM ~1 split
    out = open(os.path.join(work, "points.npy"), "wb")
    header = np.lib.format.header_data_from_array_1_0(
        np.empty((0, d), np.float32))
    header["shape"] = (n, d)
    np.lib.format.write_array_header_1_0(out, header)
    chunk = 4_000_000
    for lo in range(0, n, chunk):
        m = min(chunk, n - lo)
        out.write(rng.standard_normal(size=(m, d), dtype=np.float32).tobytes())
    out.close()

    t_cpu = run_kmeans_job(work, "cpu", per_split)
    log(f"[kmeans] cpu-batch full job ({n:,} pts): {t_cpu:.2f}s "
        f"({n / t_cpu / 1e6:.2f}M rec/s)")
    rows["kmeans_cpu_batch_job_s"] = round(t_cpu, 3)
    rows["kmeans_cpu_batch_rec_per_s"] = round(n / t_cpu)

    if not TPU_OK:
        rows["kmeans_n_points"] = n
        return t_cpu, 0.0

    t_cold = run_kmeans_job(work, "tpu", per_split)
    log(f"[kmeans] tpu COLD full job (read+stage+compile): {t_cold:.2f}s")
    rows["kmeans_tpu_cold_job_s"] = round(t_cold, 3)

    warm = [run_kmeans_job(work, "tpu", per_split) for _ in range(3)]
    t_warm = sum(warm) / len(warm)
    log(f"[kmeans] tpu WARM full jobs: mean {t_warm:.2f}s "
        f"(min {min(warm):.2f} max {max(warm):.2f}) — variance is host-side "
        f"job machinery (split planning, reduce, commit), the device work "
        f"is microseconds at this size")
    rows["kmeans_tpu_warm_job_s"] = round(t_warm, 3)
    rows["kmeans_tpu_warm_job_min_s"] = round(min(warm), 3)
    rows["kmeans_tpu_warm_job_max_s"] = round(max(warm), 3)
    rows["kmeans_tpu_warm_rec_per_s"] = round(n / t_warm)

    # reference execution model (per-record map calls) on a small full job
    sub = os.path.join(work, "sub")
    os.makedirs(sub, exist_ok=True)
    pts = np.lib.format.open_memmap(os.path.join(work, "points.npy"),
                                    mode="r")
    np.save(os.path.join(sub, "points.npy"),
            np.ascontiguousarray(pts[:n_record]))
    np.save(os.path.join(sub, "cents.npy"), cents)
    t_rec = run_kmeans_job(sub, "cpu-record", n_record)
    log(f"[kmeans] cpu PER-RECORD full job ({n_record:,} pts): {t_rec:.2f}s "
        f"({n_record / t_rec / 1e3:.1f}k rec/s — the reference's "
        f"one-record-per-map()-call model)")
    rows["kmeans_cpu_per_record_rec_per_s"] = round(n_record / t_rec)
    rows["kmeans_n_points"] = n
    return t_cpu, t_warm


# ------------------------------------------------------- kmeans pipeline


def bench_kmeans_pipeline(rows: dict) -> None:
    """The DAG engine's acceptance row: kmeans-10-rounds as ONE
    pipeline submission (loop node, round barrier, per-round versioned
    centroid files, zero cache clears) vs 10 SEQUENTIAL job
    submissions (today's iterative driver: per-round client submit +
    poll + clear_centroid_cache). Both run the identical per-round job
    on the same in-process mini cluster (CPU mapper — this measures
    control-plane and staging overhead, not kernels), and the final
    centroids must be byte-identical. The win is the eliminated
    per-round submit+schedule+poll overhead, reported per round."""
    from tpumr.fs import get_filesystem
    from tpumr.mapred.job_client import JobClient
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.mini_cluster import MiniMRCluster
    from tpumr.ops.kmeans import clear_centroid_cache, \
        clear_pipeline_caches
    from tpumr.pipeline import JobGraph, PipelineClient

    rounds = 10
    n = 60_000 if SMALL else 400_000
    d, k = 8, 8
    per_split = n // 4
    work = tempfile.mkdtemp(prefix="tpumr-bench-kmpipe-")
    rng = np.random.default_rng(11)
    pts = rng.standard_normal(size=(n, d), dtype=np.float32)
    np.save(os.path.join(work, "points.npy"), pts)
    cents0 = rng.standard_normal(size=(k, d), dtype=np.float32)

    def round_conf_dict(tag: str) -> dict:
        return {
            "mapred.input.dir": f"file://{work}/points.npy",
            "mapred.output.dir": f"file://{work}/{tag}-out-r{{round}}",
            "mapred.input.format.class":
                "tpumr.mapred.input_formats.DenseInputFormat",
            "tpumr.dense.split.rows": per_split,
            "mapred.mapper.class": "tpumr.ops.kmeans.KMeansCpuMapper",
            "mapred.reducer.class":
                "tpumr.ops.kmeans.KMeansCentroidUpdateReducer",
            "mapred.reduce.tasks": 1,
            "tpumr.kmeans.centroids":
                f"file://{work}/{tag}-cents-r{{round}}.npy",
            "tpumr.kmeans.centroids.out":
                f"file://{work}/{tag}-cents-r{{next_round}}.npy",
            "mapred.reduce.slowstart.completed.maps": 0.0,
            "mapred.speculative.execution": False,
        }

    cluster_conf = JobConf()
    cluster_conf.set("mapred.reduce.slowstart.completed.maps", 0.0)
    with MiniMRCluster(num_trackers=2, tpu_slots=0, cpu_slots=2,
                       conf=cluster_conf) as c:
        from tpumr.pipeline.graph import expand_round
        master = c.master

        def job_exec_s(job_ids: "list[str]") -> float:
            return sum(master.jobs[j].finish_time
                       - master.jobs[j].start_time for j in job_ids)

        # --- sequential baseline: today's iterative driver shape
        np.save(os.path.join(work, "seq-cents-r0.npy"), cents0)
        seq_jobs: "list[str]" = []
        t0 = time.time()
        for r in range(rounds):
            clear_centroid_cache()   # the per-round staleness flush the
            # pipeline path no longer needs (versioned paths)
            conf = c.create_job_conf()
            for key, v in expand_round(round_conf_dict("seq"),
                                       r).items():
                conf.set(key, v)
            running = JobClient(conf).submit_job(conf)
            st = running.wait_for_completion(poll_s=0.05)
            assert st["state"] == "SUCCEEDED", st
            seq_jobs.append(running.job_id)
        t_seq = time.time() - t0
        seq_exec = job_exec_s(seq_jobs)

        # --- one pipeline submission, loop node, max-rounds cutoff
        np.save(os.path.join(work, "pipe-cents-r0.npy"), cents0)
        g = JobGraph("bench-kmeans-pipeline")
        g.loop("km", round_conf_dict("pipe"), max_rounds=rounds,
               converge={"group": "KMeans",
                         "counter": "CENTROID_SHIFT_MILLI",
                         "op": "lt", "value": 0})   # never: fixed rounds
        t0 = time.time()
        running_p = PipelineClient(c.create_job_conf()).submit(g)
        st = running_p.wait_for_completion(poll_s=0.05)
        t_pipe = time.time() - t0
        assert st["state"] == "SUCCEEDED", st
        assert st["nodes"]["km"]["rounds_run"] == rounds, st
        pipe_exec = job_exec_s(st["nodes"]["km"]["jobs"])
        clear_pipeline_caches()   # teardown: ONE prefix-clear

    fs = get_filesystem(f"file://{work}")
    final_seq = fs.read_bytes(f"file://{work}/seq-cents-r{rounds}.npy")
    final_pipe = fs.read_bytes(f"file://{work}/pipe-cents-r{rounds}.npy")
    identical = final_seq == final_pipe

    win = t_seq - t_pipe
    seq_overhead = t_seq - seq_exec      # client submit+stage+poll
    pipe_overhead = t_pipe - pipe_exec   # engine advance residual
    log(f"[kmeans_pipeline] {rounds} rounds on {n:,} pts: sequential "
        f"{t_seq:.2f}s (exec {seq_exec:.2f}s, overhead "
        f"{seq_overhead:.2f}s) vs pipeline {t_pipe:.2f}s (exec "
        f"{pipe_exec:.2f}s, overhead {pipe_overhead:.2f}s) -> win "
        f"{win:.2f}s ({win / rounds * 1000:.0f} ms/round), "
        f"identical={identical}")
    rows["kmeans_pipeline_rounds"] = rounds
    rows["kmeans_pipeline_n_points"] = n
    rows["kmeans_pipeline_seq_10_jobs_s"] = round(t_seq, 3)
    rows["kmeans_pipeline_one_submission_s"] = round(t_pipe, 3)
    rows["kmeans_pipeline_seq_job_exec_s"] = round(seq_exec, 3)
    rows["kmeans_pipeline_job_exec_s"] = round(pipe_exec, 3)
    rows["kmeans_pipeline_seq_overhead_s"] = round(seq_overhead, 3)
    rows["kmeans_pipeline_engine_overhead_s"] = round(pipe_overhead, 3)
    rows["kmeans_pipeline_win_s"] = round(win, 3)
    rows["kmeans_pipeline_win_per_round_ms"] = round(win / rounds * 1000)
    rows["kmeans_pipeline_speedup"] = round(t_seq / t_pipe, 3)
    rows["kmeans_pipeline_identical_output"] = identical
    assert identical, "pipeline rounds must reproduce the sequential " \
                      "driver's centroids byte-for-byte"

    # --- devcache-affinity warm rounds: the same pipeline on the DEVICE
    # kernel path (jax is pinned to cpu in this phase — the split-cache/
    # devcache machinery is backend-agnostic), where round r's reducer
    # pre-seeds round r+1's centroids under their tag and the scheduler
    # places maps by the tag inventory trackers piggyback on heartbeats.
    # In-process mini-cluster trackers share ONE process-global devcache,
    # so what this rig measures honestly is cold-vs-warm staged bytes,
    # the warm-round HBM hit rate, and the affinity counters proving the
    # placement layer consulted (and hit) the tag index — not
    # per-tracker re-staging, which needs real multi-host trackers.
    from tpumr.core.counters import BackendCounter
    from tpumr.ops.devcache import clear_device_cache

    aff_rounds = 6

    def device_pipeline(tag: str,
                        affinity: bool) -> "tuple[list[int], dict]":
        clear_pipeline_caches()
        clear_device_cache()
        np.save(os.path.join(work, f"{tag}-cents-r0.npy"), cents0)
        dconf = round_conf_dict(tag)
        dconf["tpumr.map.kernel"] = "kmeans-assign"
        cconf = JobConf()
        cconf.set("mapred.reduce.slowstart.completed.maps", 0.0)
        cconf.set("tpumr.scheduler.affinity", affinity)
        with MiniMRCluster(num_trackers=2, tpu_slots=2, cpu_slots=0,
                           conf=cconf) as dc:
            g2 = JobGraph(f"bench-kmeans-{tag}")
            g2.loop("km", dconf, max_rounds=aff_rounds,
                    converge={"group": "KMeans",
                              "counter": "CENTROID_SHIFT_MILLI",
                              "op": "lt", "value": 0})
            st2 = PipelineClient(dc.create_job_conf()).submit(g2) \
                .wait_for_completion(poll_s=0.05)
            assert st2["state"] == "SUCCEEDED", st2
            staged = [int(dc.master.jobs[j].counters.value(
                          BackendCounter.GROUP,
                          BackendCounter.TPU_DEVICE_BYTES_STAGED))
                      for j in st2["nodes"]["km"]["jobs"]]
            sched_counters = dict(dc.master.scheduler.metrics.snapshot())
        clear_pipeline_caches()
        clear_device_cache()
        return staged, sched_counters

    staged_on, aff_counters = device_pipeline("aff", affinity=True)
    staged_off, _ = device_pipeline("affoff", affinity=False)
    final_on = fs.read_bytes(f"file://{work}/aff-cents-r{aff_rounds}.npy")
    final_off = fs.read_bytes(
        f"file://{work}/affoff-cents-r{aff_rounds}.npy")
    aff_identical = final_on == final_off
    cold = staged_on[0]
    warm = sum(staged_on[1:])
    warm_rounds = max(1, len(staged_on) - 1)
    hit_rate = sum(1 for s in staged_on[1:] if s == 0) / warm_rounds
    log(f"[kmeans_pipeline] affinity warm rounds: cold round staged "
        f"{cold:,} B, warm rounds staged {warm:,} B total over "
        f"{warm_rounds} (hbm hit rate {hit_rate:.2f}), scheduler "
        f"warm_hits={aff_counters.get('affinity_warm_hits', 0)} "
        f"defers={aff_counters.get('affinity_defers', 0)}, "
        f"identical(affinity on/off)={aff_identical}")
    rows["kmeans_pipeline_affinity_rounds"] = aff_rounds
    rows["kmeans_pipeline_affinity_cold_staged_bytes"] = cold
    rows["kmeans_pipeline_affinity_warm_staged_bytes"] = warm
    rows["kmeans_pipeline_affinity_warm_hbm_hit_rate"] = round(
        hit_rate, 3)
    rows["kmeans_pipeline_affinity_warm_hits"] = int(
        aff_counters.get("affinity_warm_hits", 0))
    rows["kmeans_pipeline_affinity_defers"] = int(
        aff_counters.get("affinity_defers", 0))
    rows["kmeans_pipeline_affinity_cold_assigns"] = int(
        aff_counters.get("affinity_cold_assigns", 0))
    rows["kmeans_pipeline_affinity_off_warm_staged_bytes"] = \
        sum(staged_off[1:])
    rows["kmeans_pipeline_affinity_identical_output"] = aff_identical
    assert cold > 0, "round 0 must stage the splits host->device"
    assert warm < cold, \
        "warm rounds must not re-stage what the caches hold " \
        f"(cold {cold} B vs warm total {warm} B)"
    assert aff_identical, "affinity placement must change WHERE maps " \
                          "run, never the centroids they produce"


# ------------------------------------------------------------- straggler


def bench_straggler(rows: dict) -> None:
    """Targeted speculation's acceptance row: one fi-injected slow map
    (``task.slow.m0`` crawls for ``tpumr.fi.task.slow.ms`` before its
    real work) in a sleep job with deliberately bimodal map runtimes,
    run three ways on identical mini clusters. OFF: the job's wall IS
    the crawl. BLANKET (``tpumr.speculative.targeted=false``): the
    reference's age-only rule rescues the job but also twins the
    healthy long maps — wasted duplicate work. TARGETED (default): the
    estimated-finish + critical-path gates twin exactly the straggler.
    Host-only — this measures the control plane, not kernels. The
    acceptance relations are asserted here, not just reported."""
    from tpumr.mapred.job_client import JobClient
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.mini_cluster import MiniMRCluster
    from tpumr.utils import fi

    slow_ms = 6000 if SMALL else 10000
    # map i sleeps lines[i] x 100 ms. m0 carries the fault AND the
    # longest split, so its crawling original pins the critical path
    # until its twin lands — the targeted pass therefore never twins
    # m1/m2 (healthy but long: exactly what blanket's age-only rule
    # wastes twins on). m3..m5 finish first and set the completed-
    # runtime mean both modes' lag gates compare against.
    lines = [30, 22, 22, 1, 1, 1]
    work = tempfile.mkdtemp(prefix="tpumr-bench-strag-")
    paths = []
    for i, n in enumerate(lines):
        p = os.path.join(work, f"in-{i}.txt")
        with open(p, "w") as f:
            f.write("x\n" * n)
        paths.append(f"file://{p}")

    def run_mode(tag: str, speculative: bool,
                 targeted: bool) -> "tuple[float, int, int, int]":
        fi.reset()   # fired-counts are per-process; each mode re-arms
        base = JobConf()
        base.set("tpumr.heartbeat.interval.ms", 100)
        with MiniMRCluster(num_trackers=3, conf=base, cpu_slots=2,
                           tpu_slots=0) as c:
            conf = c.create_job_conf()
            conf.set_input_paths(",".join(paths))
            conf.set_output_path(f"file://{work}/out-{tag}")
            # one split per file, in input order: m<i> <-> in-<i>.txt
            conf.set("mapred.min.split.size", 1 << 40)
            conf.set("mapred.mapper.class",
                     "tpumr.examples.sleep.SleepMapper")
            conf.set("mapred.reducer.class",
                     "tpumr.examples.sleep.SleepReducer")
            conf.set_num_reduce_tasks(1)
            conf.set("tpumr.sleep.map.ms", 100)
            conf.set("tpumr.sleep.reduce.ms", 100)
            conf.set("mapred.speculative.execution", speculative)
            conf.set("tpumr.speculative.targeted", targeted)
            conf.set("mapred.speculative.min.runtime.s", 0.3)
            conf.set("tpumr.fi.task.slow.m0.probability", 1.0)
            conf.set("tpumr.fi.task.slow.m0.max.failures", 1)
            conf.set("tpumr.fi.task.slow.ms", slow_ms)
            t0 = time.time()
            result = JobClient(conf).run_job(conf)
            wall = time.time() - t0
            assert result.successful, f"straggler[{tag}] job failed"
            assert fi.fired("task.slow.m0") == 1
            jip = c.master.jobs[str(result.job_id)]
            return (wall, jip.speculative_launched,
                    jip.speculative_won, jip.speculative_wasted)

    off = run_mode("off", speculative=False, targeted=True)
    blanket = run_mode("blanket", speculative=True, targeted=False)
    targeted = run_mode("targeted", speculative=True, targeted=True)

    speedup = off[0] / max(1e-9, targeted[0])
    log(f"[straggler] m0 crawls {slow_ms} ms: off {off[0]:.2f}s / "
        f"blanket {blanket[0]:.2f}s ({blanket[1]} twins, {blanket[3]} "
        f"wasted) / targeted {targeted[0]:.2f}s ({targeted[1]} twins, "
        f"{targeted[3]} wasted) -> targeted {speedup:.2f}x over off")
    rows["straggler_slow_ms"] = slow_ms
    rows["straggler_maps"] = len(lines)
    rows["straggler_off_s"] = round(off[0], 3)
    rows["straggler_blanket_s"] = round(blanket[0], 3)
    rows["straggler_targeted_s"] = round(targeted[0], 3)
    rows["straggler_targeted_speedup_vs_off"] = round(speedup, 3)
    rows["straggler_off_launched"] = off[1]
    rows["straggler_blanket_launched"] = blanket[1]
    rows["straggler_blanket_wasted"] = blanket[3]
    rows["straggler_targeted_launched"] = targeted[1]
    rows["straggler_targeted_won"] = targeted[2]
    rows["straggler_targeted_wasted"] = targeted[3]
    assert off[1] == 0, "speculation off must launch no twins"
    assert targeted[2] >= 1, "the targeted twin must win the race"
    assert speedup >= 1.3, \
        f"targeted speculation must beat speculation-off >=1.3x " \
        f"(got {speedup:.2f}x)"
    assert targeted[1] < blanket[1], \
        f"targeted must launch strictly fewer twins than blanket " \
        f"({targeted[1]} vs {blanket[1]})"


# ------------------------------------------------------------- wordcount


def bench_wordcount(rows: dict) -> None:
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.local_runner import run_job

    mb = 4 if SMALL else 200
    work = tempfile.mkdtemp(prefix="tpumr-bench-wc-")
    words = [f"word{i:04d}".encode() for i in range(4096)]
    rng = np.random.default_rng(1)
    path = os.path.join(work, "text.txt")
    with open(path, "wb") as f:
        line = b" ".join(words[i] for i in rng.integers(0, 4096, 12)) + b"\n"
        reps = mb * 1024 * 1024 // len(line)
        idx = rng.integers(0, 4096, size=(reps, 12))
        f.write(b"\n".join(b" ".join(words[j] for j in r) for r in idx))
    size = os.path.getsize(path)

    conf = JobConf()
    conf.set_job_name("bench-wordcount")
    conf.set_input_paths(f"file://{path}")
    conf.set_output_path(f"file://{work}/out")
    from tpumr.mapred.input_formats import RawTextInputFormat
    conf.set_input_format(RawTextInputFormat)
    conf.set_map_kernel("wordcount")
    conf.set("mapred.reducer.class", "tpumr.examples.basic.LongSumReducer")
    conf.set("mapred.combiner.class", "tpumr.examples.basic.LongSumReducer")
    conf.set_num_reduce_tasks(1)
    t0 = time.time()
    result = run_job(conf)
    dt = time.time() - t0
    assert result.successful
    log(f"[wordcount] {size / 1e6:.0f} MB full job (vectorized batch "
        f"tokenize): {dt:.2f}s ({size / dt / 1e6:.0f} MB/s)")
    rows["wordcount_job_s"] = round(dt, 3)
    rows["wordcount_mb_per_s"] = round(size / dt / 1e6, 1)


# -------------------------------------------------------------------- pi


def bench_pi(rows: dict) -> None:
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.local_runner import run_job

    samples = 10_000_000 if SMALL else 400_000_000
    maps = 8
    work = tempfile.mkdtemp(prefix="tpumr-bench-pi-")
    path = os.path.join(work, "seeds.txt")
    with open(path, "w") as f:
        for m in range(maps):
            f.write(f"{m} {samples // maps}\n")

    def run(mode: str) -> float:
        from tpumr.mapred.input_formats import NLineInputFormat
        conf = JobConf()
        conf.set_job_name(f"bench-pi-{mode}")
        conf.set_input_paths(f"file://{path}")
        conf.set_output_path(f"file://{work}/out-{mode}-{time.time_ns()}")
        conf.set_input_format(NLineInputFormat)
        conf.set("mapred.line.input.format.linespermap", 1)
        conf.set_map_kernel("pi-sampler")
        conf.set("mapred.reducer.class",
                 "tpumr.examples.basic.LongSumReducer")
        conf.set_num_reduce_tasks(1)
        if mode == "tpu":
            conf.set("tpumr.local.run.on.tpu", True)
        t0 = time.time()
        assert run_job(conf).successful
        return time.time() - t0

    t_cpu = run("cpu")
    rows["pi_cpu_batch_job_s"] = round(t_cpu, 3)
    rows["pi_samples"] = samples
    if not TPU_OK:
        log(f"[pi] {samples:,} samples: cpu-batch {t_cpu:.2f}s "
            f"(tpu skipped: backend unavailable)")
        return
    t_tpu = run("tpu")
    t_tpu_warm = run("tpu")  # compile cached
    log(f"[pi] {samples:,} samples: tpu {t_tpu:.2f}s (warm "
        f"{t_tpu_warm:.2f}s), cpu-batch {t_cpu:.2f}s -> "
        f"{t_cpu / t_tpu_warm:.1f}x")
    rows["pi_tpu_job_s"] = round(t_tpu_warm, 3)


# ---------------------------------------------------------------- matmul


def bench_matmul(rows: dict) -> None:
    """Blocked C = A @ B as a map-only job (BASELINE workload 4): each
    map owns a row block of A, B rides as a side file, C blocks leave
    through SequenceFile outputs."""
    from tpumr.mapred.input_formats import DenseInputFormat
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.local_runner import run_job
    from tpumr.mapred.output_formats import SequenceFileOutputFormat
    from tpumr.ops.matmul import clear_b_cache

    n = 1024 if SMALL else 4096
    work = tempfile.mkdtemp(prefix="tpumr-bench-mm-")
    rng = np.random.default_rng(2)
    np.save(os.path.join(work, "a.npy"),
            rng.standard_normal(size=(n, n), dtype=np.float32))
    np.save(os.path.join(work, "b.npy"),
            rng.standard_normal(size=(n, n), dtype=np.float32))

    def run(mode: str) -> float:
        clear_b_cache()
        conf = JobConf()
        conf.set_job_name(f"bench-matmul-{mode}")
        conf.set_input_paths(f"file://{work}/a.npy")
        conf.set_output_path(f"file://{work}/out-{mode}-{time.time_ns()}")
        conf.set_input_format(DenseInputFormat)
        conf.set("tpumr.dense.split.rows", n // 4)
        conf.set("tpumr.matmul.b", f"file://{work}/b.npy")
        conf.set_map_kernel("matmul-block")
        conf.set_output_format(SequenceFileOutputFormat)
        conf.set_num_reduce_tasks(0)
        if mode == "tpu":
            conf.set("tpumr.local.run.on.tpu", True)
        t0 = time.time()
        assert run_job(conf).successful
        return time.time() - t0

    t_cpu = run("cpu")
    rows["matmul_n"] = n
    rows["matmul_cpu_batch_job_s"] = round(t_cpu, 3)
    if not TPU_OK:
        log(f"[matmul] {n}x{n}: cpu-batch {t_cpu:.2f}s "
            f"(tpu skipped: backend unavailable)")
        return
    t_tpu_cold = run("tpu")
    t_tpu = run("tpu")        # compile cached
    flops = 2 * n ** 3
    log(f"[matmul] {n}x{n} @ {n}x{n} full job: tpu {t_tpu:.2f}s warm "
        f"({flops / t_tpu / 1e12:.2f} TFLOP/s incl. job machinery, cold "
        f"{t_tpu_cold:.2f}s), cpu-batch {t_cpu:.2f}s -> "
        f"{t_cpu / t_tpu:.1f}x")
    rows["matmul_tpu_job_s"] = round(t_tpu, 3)
    rows["matmul_tpu_cold_job_s"] = round(t_tpu_cold, 3)


# -------------------------------------------------------------- terasort


def _teragen_ok(gen_dir: str, n: int) -> bool:
    """The gen-dir sentinel carries the record count: a kill
    mid-teragen (or a scale flip across runs) must force regeneration,
    not benchmark a truncated/mis-sized dataset as if it were n
    records."""
    try:
        with open(os.path.join(gen_dir, "_BENCH_GEN_OK")) as f:
            return f.read().strip() == str(n)
    except OSError:
        return False


def bench_terasort(rows: dict) -> None:
    from tpumr.examples.terasort import make_terasort_conf
    from tpumr.mapred.local_runner import run_job

    n = 100_000 if SMALL else 2_000_000
    # gen data lives in the shared dir so the terasort_fresh PHASE (a
    # separate process, by design — see bench_terasort_fresh) can reuse it
    shared = os.environ.get("BENCH_SHARED_DIR") or tempfile.mkdtemp(
        prefix="tpumr-bench-shared-")
    work = os.path.join(shared, "ts")
    os.makedirs(work, exist_ok=True)
    from tpumr.cli import main as cli_main
    sentinel = os.path.join(work, "gen", "_BENCH_GEN_OK")
    if not _teragen_ok(os.path.join(work, "gen"), n):
        import shutil
        shutil.rmtree(os.path.join(work, "gen"), ignore_errors=True)
        t0 = time.time()
        assert cli_main(["examples", "teragen", str(n),
                         f"file://{work}/gen", "-m", "4"]) == 0
        with open(sentinel, "w") as f:
            f.write(str(n))
        log(f"[terasort] teragen {n:,} records: {time.time() - t0:.2f}s")

    def run(device: bool) -> float:
        mode = "device" if device else "host"
        conf = make_terasort_conf(f"file://{work}/gen",
                                  f"file://{work}/out-{mode}-"
                                  f"{time.time_ns()}", 4,
                                  device_shuffle=device)
        t0 = time.time()
        assert run_job(conf).successful
        return time.time() - t0

    t_host = run(False)
    rows["terasort_host_job_s"] = round(t_host, 3)
    rows["terasort_records"] = n
    if not TPU_OK:
        log(f"[terasort] {n:,} records: host shuffle {t_host:.2f}s "
            f"(device skipped: backend unavailable)")
        return
    t_dev_cold = run(True)    # pays the dest/exchange/sort XLA compiles
    t_dev = run(True)         # compile cache warm: the steady state
    log(f"[terasort] {n:,} records ({n * 100 / 1e6:.0f} MB): host shuffle "
        f"{t_host:.2f}s, device shuffle cold {t_dev_cold:.2f}s / warm "
        f"{t_dev:.2f}s -> warm {t_host / t_dev:.2f}x")
    rows["terasort_device_cold_job_s"] = round(t_dev_cold, 3)
    rows["terasort_device_job_s"] = round(t_dev, 3)

    # the fresh-process compile-cache row is its OWN phase
    # (bench_terasort_fresh): a single tunneled TPU is exclusive, so the
    # fresh process can only initialize the backend after THIS process
    # has exited — the orchestrator sequences that.


def bench_terasort_fresh(rows: dict) -> None:
    """The production cold path: a FRESH worker process (this one — the
    orchestrator runs every phase in its own subprocess) running the
    device terasort with the persistent XLA compilation cache populated
    by the preceding terasort phase (shared ``TPUMR_JAX_CACHE_DIR``).
    Measures what a brand-new worker pays when the compile bill is
    already settled — the JVM-reuse story (``JvmManager.java:322``) in
    XLA terms. A separate phase because the tunneled TPU is EXCLUSIVE:
    a subprocess spawned while a parent held the backend can never
    initialize (``UNAVAILABLE`` after ~25 min — the round-4 failure mode
    this design removes)."""
    from tpumr.examples.terasort import make_terasort_conf
    from tpumr.mapred.local_runner import run_job

    n = 100_000 if SMALL else 2_000_000
    shared = os.environ.get("BENCH_SHARED_DIR", "")
    gen = os.path.join(shared, "ts", "gen")
    if not (shared and _teragen_ok(gen, n)):
        # sentinel missing or wrong record count: the terasort phase was
        # skipped, failed, or killed mid-teragen — a plausible-looking
        # number measured on truncated data is worse than no number
        log("[terasort-fresh] no complete shared teragen data (terasort "
            "phase skipped/failed?) — skipping")
        rows["terasort_device_fresh_process_cached_s"] = "skipped: no data"
        return
    conf = make_terasort_conf(
        f"file://{gen}",
        f"file://{os.path.join(shared, 'ts')}/out-fresh-{time.time_ns()}",
        4, device_shuffle=True)
    t0 = time.time()
    assert run_job(conf).successful
    t_fresh = time.time() - t0
    log(f"[terasort-fresh] fresh-process device job with inherited "
        f"compilation cache: {t_fresh:.2f}s (compare "
        f"terasort_device_cold_job_s — the same compiles paid in-process)")
    rows["terasort_device_fresh_process_cached_s"] = round(t_fresh, 3)


# ---------------------------------------------------------------- codecs


def bench_codecs(rows: dict) -> None:
    """Shuffle/spill codec cost (VERDICT r3 Next #5): stdlib zlib vs the
    native tlz codec on the two spill regimes — text-like (wordcount
    spills) and incompressible (terasort keys). Host-side; runs even
    when the TPU is down."""
    import zlib
    from tpumr.io.compress import TlzCodec

    mb = 8 if SMALL else 48
    rng = np.random.default_rng(3)
    words = [f"word{i:04d}".encode() for i in range(4096)]
    text = b"".join(words[i] + b"\t" + str(i % 100).encode() + b"\n"
                    for i in rng.integers(0, 4096,
                                          mb * 1024 * 1024 // 12))
    text = text[:mb * 1024 * 1024]
    rand = rng.integers(0, 256, size=mb * 1024 * 1024,
                        dtype=np.uint8).tobytes()

    def measure(tag: str, data: bytes, comp, decomp) -> None:
        t0 = time.time()
        c = comp(data)
        t1 = time.time()
        d = decomp(c)
        t2 = time.time()
        assert d == data
        rows[f"codec_{tag}_ratio"] = round(len(c) / len(data), 3)
        rows[f"codec_{tag}_compress_mb_s"] = round(
            len(data) / 1e6 / (t1 - t0), 1)
        rows[f"codec_{tag}_decompress_mb_s"] = round(
            len(data) / 1e6 / (t2 - t1), 1)

    for kind, data in (("text", text), ("random", rand)):
        measure(f"zlib1_{kind}", data,
                lambda d: zlib.compress(d, 1), zlib.decompress)
        if TlzCodec.available():
            c = TlzCodec()
            measure(f"tlz_{kind}", data, c.compress, c.decompress)
    rows["codec_tlz_native"] = TlzCodec.available()
    log(f"[codecs] text: zlib1 {rows['codec_zlib1_text_compress_mb_s']}"
        f" MB/s ratio {rows['codec_zlib1_text_ratio']}"
        + (f" | tlz {rows.get('codec_tlz_text_compress_mb_s')} MB/s "
           f"ratio {rows.get('codec_tlz_text_ratio')}"
           if TlzCodec.available() else " | tlz unavailable")
        + f"; random: zlib1 "
          f"{rows['codec_zlib1_random_compress_mb_s']} MB/s"
        + (f" | tlz {rows.get('codec_tlz_random_compress_mb_s')} MB/s"
           if TlzCodec.available() else ""))


# ---------------------------------------------------------- kernel MFU


#: bf16 matmul peak FLOP/s per chip by device_kind substring. Sources:
#: public TPU spec sheets (v4 275, v5e 197, v5p 459, v6e 918 TFLOP/s).
_PEAK_BF16 = (("v6", 918e12), ("v5 lite", 197e12), ("v5e", 197e12),
              ("v5", 459e12), ("v4", 275e12))


def _peak_for(kind: str) -> float | None:
    k = kind.lower()
    for sub, peak in _PEAK_BF16:
        if sub in k:
            return peak
    return None


def bench_kernels(rows: dict) -> None:
    """ON-CHIP kernel efficiency, isolated from job machinery AND from
    the tunnel: each kernel runs its iterations chained inside one
    jitted ``lax.fori_loop`` and is timed by TWO-POINT DIFFERENCING —
    the same chain compiled at a short and a long iteration count, each
    run fetched as a SCALAR reduction via ``np.asarray`` (forcing a real
    device→host roundtrip; on this tunneled harness
    ``block_until_ready`` returns before the device work is actually
    done, which round-4 smoke exposed as a 192% "MFU"). Per-iteration
    time = (t_long − t_short)/(I_HI − I_LO): the constant dispatch +
    RPC + fetch cost cancels in the difference. This is the measurement
    VERDICT r3 Weak #4 asked for: records/s/chip and FLOP/s vs peak per
    kernel, separate from job wall-clocks."""
    import jax
    from jax import lax
    import jax.numpy as jnp

    kind = jax.devices()[0].device_kind
    backend = jax.default_backend()
    peak = _peak_for(kind)
    rows["kernel_device_kind"] = kind
    # wide spread on the device: at 4096³ one iteration is ~1 ms, so a
    # 96-iteration delta (~100 ms) stands clear of per-call tunnel
    # jitter; the loop bound is a compile-time constant in ONE While op,
    # so the long chain costs no extra compile
    i_lo, i_hi = (2, 6) if backend == "cpu" else (8, 104)
    rows["kernel_timing_method"] = (
        f"two-point differenced chained fori_loop ({i_lo} vs {i_hi} "
        f"iters), scalar np.asarray fetch, median of 3")

    def timed_chain(build, *args):
        """``build(iters)`` returns the chain function (ending in a
        scalar reduction). Compile both lengths, then difference; the
        median over 3 passes rejects one-off tunnel hiccups."""
        from tpumr.utils import progress
        fn_lo = jax.jit(build(i_lo))
        fn_hi = jax.jit(build(i_hi))
        np.asarray(fn_lo(*args))        # compile + warm both lengths
        progress.tick(0, "kernel-warm-lo")
        np.asarray(fn_hi(*args))
        progress.tick(0, "kernel-warm-hi")
        diffs = []
        for _ in range(3):
            t0 = time.time()
            np.asarray(fn_lo(*args))
            t_lo = time.time() - t0
            t0 = time.time()
            np.asarray(fn_hi(*args))
            t_hi = time.time() - t0
            progress.tick(0, "kernel-pass")
            per = (t_hi - t_lo) / (i_hi - i_lo)
            if per > 0:
                diffs.append(per)
        if not diffs:
            # noise swamped the compute delta in every pass — surface
            # "unmeasurable", never a NaN that poisons the JSON artifact
            return None
        diffs.sort()
        return diffs[(len(diffs) - 1) // 2]   # lower median

    # --- matmul: the MXU headline. n=4096 f32 accumulate from bf16.
    n = 1024 if (SMALL or backend == "cpu") else 4096
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), jnp.float32)
    b16 = jax.random.normal(key, (n, n), jnp.bfloat16)
    bf32 = b16.astype(jnp.float32)

    def mm_build(dtype_in):
        def build(iters):
            def chain(y, b):
                def body(_, acc):
                    acc = jnp.dot(acc.astype(dtype_in), b,
                                  preferred_element_type=jnp.float32)
                    return acc * (1.0 / n)   # keep magnitudes bounded
                return jnp.sum(lax.fori_loop(0, iters, body, y))
            return chain
        return build

    flops = 2.0 * n ** 3
    t16 = timed_chain(mm_build(jnp.bfloat16), a, b16)
    t32 = timed_chain(mm_build(jnp.float32), a, bf32)
    rows["kernel_matmul_n"] = n
    if t16 is None:
        rows["kernel_matmul_bf16_onchip_s"] = "unmeasurable: noise"
        log("[kernels] bf16 matmul timing unmeasurable (noise swamped "
            "the compute delta in all passes)")
    else:
        r16 = flops / t16
        rows["kernel_matmul_bf16_onchip_s"] = round(t16, 6)
        rows["kernel_matmul_bf16_tflops"] = round(r16 / 1e12, 2)
        if peak:
            rows["kernel_matmul_bf16_mfu"] = round(r16 / peak, 3)
        log(f"[kernels] matmul {n}^3 on-chip: bf16 {t16 * 1e3:.2f} ms/iter "
            f"= {r16 / 1e12:.1f} TFLOP/s"
            + (f" (MFU {r16 / peak:.1%} of {kind})" if peak
               else f" ({kind})"))
    if t32 is None:
        rows["kernel_matmul_f32_onchip_s"] = "unmeasurable: noise"
        log("[kernels] f32 matmul timing unmeasurable")
    else:
        r32 = flops / t32
        rows["kernel_matmul_f32_onchip_s"] = round(t32, 6)
        rows["kernel_matmul_f32_tflops"] = round(r32 / 1e12, 2)
        log(f"[kernels] matmul {n}^3 on-chip: f32 {t32 * 1e3:.2f} ms/iter "
            f"= {r32 / 1e12:.1f} TFLOP/s")

    # --- kmeans-assign: the north-star map kernel (distance matmul +
    # argmin + partial-sum matmul), iterated as real Lloyd rounds.
    n_pts = 200_000 if (SMALL or backend == "cpu") else 8_000_000
    d, k = 16, 16
    pts = jax.random.normal(key, (n_pts, d), jnp.float32)
    cents = jax.random.normal(key, (k, d), jnp.float32)

    def km_build(iters):
        def chain(p, c0):
            def body(_, c):
                x2 = jnp.sum(p * p, axis=1, keepdims=True)
                c2 = jnp.sum(c * c, axis=1)
                d2 = x2 - 2.0 * jnp.dot(p, c.T,
                                        preferred_element_type=jnp.float32) \
                    + c2[None, :]
                assign = jnp.argmin(d2, axis=1)
                onehot = jax.nn.one_hot(assign, k, dtype=p.dtype)
                sums = jnp.dot(onehot.T, p,
                               preferred_element_type=jnp.float32)
                counts = jnp.sum(onehot, axis=0)
                return sums / jnp.maximum(counts, 1.0)[:, None]
            return jnp.sum(lax.fori_loop(0, iters, body, c0))
        return chain

    t_km = timed_chain(km_build, pts, cents)
    km_flops = 4.0 * n_pts * k * d      # two [n,d]x[d,k]-class matmuls
    rows["kernel_kmeans_n_points"] = n_pts
    if t_km is None:
        rows["kernel_kmeans_onchip_s"] = "unmeasurable: noise"
        log("[kernels] kmeans timing unmeasurable")
    else:
        rows["kernel_kmeans_onchip_s"] = round(t_km, 6)
        rows["kernel_kmeans_mrec_per_s"] = round(n_pts / t_km / 1e6, 1)
        rows["kernel_kmeans_tflops"] = round(km_flops / t_km / 1e12, 2)
        log(f"[kernels] kmeans-assign {n_pts / 1e6:.0f}M pts on-chip: "
            f"{t_km * 1e3:.2f} ms/round = {n_pts / t_km / 1e6:.0f} M rec/s "
            f"({km_flops / t_km / 1e12:.2f} TFLOP/s — HBM-bound at d={d}: "
            f"arith intensity ~{4 * k / (2 * 4):.0f} FLOP/byte)")

    # --- the PALLAS assign kernel head-to-head vs XLA's fusion (the
    # ops/kmeans.py design claim: XLA wins at narrow d because Mosaic's
    # 128-lane tile pads d→128; pallas stays selectable for wide d).
    # Device-only: interpret mode on cpu measures the interpreter.
    if backend != "cpu":
        from tpumr.ops.kmeans import pallas_assign

        def kmp_build(iters):
            def chain(p, c0):
                def body(i, acc):
                    a = pallas_assign(p, c0 + (0.0 * i))
                    return acc + jnp.sum(a)
                return lax.fori_loop(0, iters, body, jnp.int32(0))
            return chain

        try:
            t_kp = timed_chain(kmp_build, pts, cents)
        except Exception as e:  # noqa: BLE001 — a Mosaic lowering gap
            rows["kernel_kmeans_pallas_onchip_s"] = \
                f"failed: {type(e).__name__}"
            log(f"[kernels] pallas assign failed to lower: {e}")
        else:
            if t_kp is None:
                rows["kernel_kmeans_pallas_onchip_s"] = \
                    "unmeasurable: noise"
            else:
                rows["kernel_kmeans_pallas_onchip_s"] = round(t_kp, 6)
                rows["kernel_kmeans_pallas_mrec_per_s"] = round(
                    n_pts / t_kp / 1e6, 1)
                log(f"[kernels] pallas assign {n_pts / 1e6:.0f}M pts: "
                    f"{t_kp * 1e3:.2f} ms/round "
                    f"({n_pts / t_kp / 1e6:.0f} M rec/s) vs XLA "
                    f"{(t_km or 0) * 1e3:.2f} ms — measured basis for "
                    f"the d={d} XLA-default choice")

    # --- device sort + permutation-apply: the shuffle hot op (terasort
    # path sorts uint32 key columns, then gathers rows into order).
    n_rec = 200_000 if (SMALL or backend == "cpu") else 4_000_000
    cols = jax.random.bits(key, (n_rec, 3), jnp.uint32)

    def sort_build(iters):
        def chain(c0):
            def body(i, c):
                order = jnp.lexsort((c[:, 2], c[:, 1], c[:, 0]))
                # re-randomize after the gather so every iteration sorts
                # random data, not the previous iteration's sorted output
                return c[order] ^ (jnp.uint32(2654435761) * (i + 1))
            return jnp.sum(lax.fori_loop(0, iters, body, c0),
                           dtype=jnp.uint32)
        return chain

    t_sort = timed_chain(sort_build, cols)
    rows["kernel_sort_n_records"] = n_rec
    if t_sort is None:
        rows["kernel_sort_onchip_s"] = "unmeasurable: noise"
        log("[kernels] sort timing unmeasurable")
    else:
        rows["kernel_sort_onchip_s"] = round(t_sort, 6)
        rows["kernel_sort_mrec_per_s"] = round(n_rec / t_sort / 1e6, 1)
        log(f"[kernels] lexsort+apply {n_rec / 1e6:.1f}M 96-bit keys "
            f"on-chip: {t_sort * 1e3:.2f} ms = "
            f"{n_rec / t_sort / 1e6:.1f} M rec/s")


# --------------------------------------------------------------- chained


def bench_chained(rows: dict) -> None:
    """Device-output chaining (tpumr/mapred/device_output.py): job 2
    consumes job 1's C blocks straight from HBM. The row the r3 verdict
    asked for: consumer staged bytes == 0, plus the wall-clock delta."""
    from tpumr.core.counters import BackendCounter
    from tpumr.mapred.input_formats import DenseInputFormat
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.local_runner import run_job
    from tpumr.mapred.output_formats import DenseNpyOutputFormat
    from tpumr.ops.matmul import clear_b_cache

    n = 1024 if SMALL else 4096
    work = tempfile.mkdtemp(prefix="tpumr-bench-chain-")
    rng = np.random.default_rng(9)
    np.save(os.path.join(work, "a.npy"),
            rng.standard_normal(size=(n, n), dtype=np.float32))
    np.save(os.path.join(work, "b.npy"),
            rng.standard_normal(size=(n, n), dtype=np.float32))

    def run(inp: str, out: str, chained: bool) -> tuple[float, int]:
        from tpumr.mapred.tpu_runner import clear_split_caches
        if not chained:
            # the control must hit NEITHER the published device outputs
            # (tpumr.tpu.output.cache=false below) NOR the input split
            # cache warmed as a side effect of the chained run — both
            # live in the per-device LRU this clears
            clear_split_caches()
        clear_b_cache()
        conf = JobConf()
        conf.set_job_name("bench-chain")
        conf.set_input_paths(inp)
        conf.set_output_path(out)
        conf.set_input_format(DenseInputFormat)
        conf.set_output_format(DenseNpyOutputFormat)
        conf.set("tpumr.dense.split.rows", n // 4)
        conf.set("tpumr.matmul.b", f"file://{work}/b.npy")
        conf.set_map_kernel("matmul-block")
        conf.set_num_reduce_tasks(0)
        conf.set("tpumr.local.run.on.tpu", True)
        if not chained:
            conf.set("tpumr.tpu.output.cache", False)
        log(f"[chained] starting job: {inp} -> {out} "
            f"(chained={chained})")
        t0 = time.time()
        result = run_job(conf)
        dt = time.time() - t0
        assert result.successful, f"chain job failed: {result.error}"
        staged = result.counters.value(
            BackendCounter.GROUP, BackendCounter.TPU_DEVICE_BYTES_STAGED)
        log(f"[chained] job done in {dt:.2f}s, staged {staged} bytes")
        return dt, staged

    t1, staged1 = run(f"file://{work}/a.npy", f"file://{work}/c", True)
    t2, staged2 = run(f"file://{work}/c", f"file://{work}/d", True)
    # the unchained control: same consumer job forced to re-stage C
    t2u, staged2u = run(f"file://{work}/c", f"file://{work}/d2", False)
    log(f"[chained] matmul {n}: producer {t1:.2f}s (staged "
        f"{staged1 / 1e6:.0f} MB), chained consumer {t2:.2f}s staged "
        f"{staged2} bytes, unchained consumer {t2u:.2f}s (staged "
        f"{staged2u / 1e6:.0f} MB) -> chaining saves "
        f"{t2u - t2:.2f}s/job")
    rows["chained_producer_job_s"] = round(t1, 3)
    rows["chained_consumer_job_s"] = round(t2, 3)
    rows["chained_consumer_staged_bytes"] = int(staged2)
    rows["chained_unchained_consumer_job_s"] = round(t2u, 3)
    rows["chained_unchained_staged_bytes"] = int(staged2u)


# ---------------------------------------------------------------- hybrid


def bench_hybrid(rows: dict) -> None:
    """The heart of the reference, measured end-to-end: the profiling
    hybrid scheduler (Shirahata) runs each job's maps on BOTH pools,
    measures per-backend mean runtimes, and skews placement by the
    acceleration factor. On this harness kmeans (compute-heavy, tiny
    map outputs) measures accel >> 1 and lands mostly on the TPU pool;
    blocked matmul ships its full N^2 output back over the tunnel
    (bandwidth-bound), measures accel < 1, and the CPU pool carries it —
    the hybrid premise working in both directions."""
    from tpumr.core.counters import BackendCounter
    from tpumr.mapred.input_formats import DenseInputFormat
    from tpumr.mapred.job_client import JobClient
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.mini_cluster import MiniMRCluster
    from tpumr.mapred.output_formats import SequenceFileOutputFormat
    from tpumr.ops.kmeans import clear_centroid_cache
    from tpumr.ops.matmul import clear_b_cache

    work = tempfile.mkdtemp(prefix="tpumr-bench-hybrid-")
    rng = np.random.default_rng(4)
    # split sizes MATCH the earlier kmeans/matmul workloads so their XLA
    # compiles are reused — the per-backend means then measure steady-
    # state task runtimes, not one first-task compile (the reference's
    # mean-over-all-attempts profiling has the same cold-start skew)
    n_km, d, k = (2_000_000 if SMALL else 32_000_000), 16, 16
    np.save(os.path.join(work, "cents.npy"),
            rng.standard_normal(size=(k, d), dtype=np.float32))
    out = open(os.path.join(work, "points.npy"), "wb")
    header = np.lib.format.header_data_from_array_1_0(
        np.empty((0, d), np.float32))
    header["shape"] = (n_km, d)
    np.lib.format.write_array_header_1_0(out, header)
    for lo in range(0, n_km, 2_000_000):
        m = min(2_000_000, n_km - lo)
        out.write(rng.standard_normal(size=(m, d), dtype=np.float32).tobytes())
    out.close()
    n_mm = 1024 if SMALL else 4096
    np.save(os.path.join(work, "a.npy"),
            rng.standard_normal(size=(n_mm, n_mm), dtype=np.float32))
    np.save(os.path.join(work, "b.npy"),
            rng.standard_normal(size=(n_mm, n_mm), dtype=np.float32))

    def run_and_profile(c, conf, tag, out_suffix=""):
        clear_centroid_cache()
        clear_b_cache()
        if out_suffix:
            conf.set_output_path(conf.get("mapred.output.dir") + out_suffix)
        t0 = time.time()
        result = JobClient(conf).run_job(conf)
        dt = time.time() - t0
        assert result.successful, f"hybrid {tag} failed: {result.error}"
        jip = c.master.jobs.get(str(result.job_id))
        accel = jip.acceleration_factor() if jip is not None else 0.0
        tpu = result.counters.value(BackendCounter.GROUP,
                                    BackendCounter.TPU_MAP_TASKS)
        cpu = result.counters.value(BackendCounter.GROUP,
                                    BackendCounter.CPU_MAP_TASKS)
        # placement trace in assignment order (TaskReport stamping,
        # ≈ JobTracker.java:3414-3433): the convergence signature is the
        # all-TPU TAIL once the starvation rule / minimizer kicks in
        tail = 0
        seq = ""
        if jip is not None:
            placements = sorted(
                ((t.report.start_time or 0.0, bool(t.report.run_on_tpu))
                 for t in jip.maps), key=lambda p: p[0])
            seq = "".join("T" if p[1] else "c" for p in placements)
            for b in reversed(seq):
                if b != "T":
                    break
                tail += 1
        log(f"[hybrid] {tag}: accel factor {accel:.2f}, placement "
            f"tpu={tpu} cpu={cpu}, assignment order {seq}, "
            f"all-TPU tail {tail}, job {dt:.2f}s")
        rows[f"hybrid_{tag}_accel"] = round(accel, 3)
        rows[f"hybrid_{tag}_tpu_maps"] = tpu
        rows[f"hybrid_{tag}_cpu_maps"] = cpu
        rows[f"hybrid_{tag}_placement_seq"] = seq
        rows[f"hybrid_{tag}_tpu_tail"] = tail

    # The reference authors' exact single-node config: ONE tracker with
    # 3 CPU + 1 TPU map slots (conf/mapred-site.xml:23-33), optional
    # scheduling on. With 8 maps of 4M rows the first wave fills the 4
    # slots; by the time they finish both backends have profiles, the
    # warm accel factor is >> 1, pending (4) < accel x 1 x 1 — and the
    # tail of the job converges to the TPU pool.
    base = JobConf()
    base.set("mapred.jobtracker.map.optionalscheduling", True)
    with MiniMRCluster(num_trackers=1, cpu_slots=3, tpu_slots=1,
                       conf=base) as c:
        conf = c.create_job_conf()
        conf.set_job_name("hybrid-kmeans")
        conf.set_input_paths(f"file://{work}/points.npy")
        conf.set_output_path(f"file://{work}/out-km")
        conf.set_input_format(DenseInputFormat)
        # Twice as many maps as the tracker has slots: the starvation
        # rule can only fire while maps are still PENDING, so the job
        # must outlast the first assignment wave (round-2 BENCH_r02
        # structurally couldn't converge — every map was assigned before
        # any profile existed). 4M-row splits keep per-task device
        # compute large enough that the warm accel factor clears 1 by a
        # wide margin (tiny splits drown in per-task tunnel roundtrips).
        conf.set("tpumr.dense.split.rows", 4_000_000 if not SMALL
                 else 250_000)
        conf.set("tpumr.kmeans.centroids", f"file://{work}/cents.npy")
        conf.set_map_kernel("kmeans-assign")
        conf.set("mapred.reducer.class",
                 "tpumr.examples.basic.CentroidReducer")
        conf.set_num_reduce_tasks(1)
        # round 1 pays cold staging per TPU task (a single-pass job is
        # upload-bound on a tunneled chip); round 2 of the ITERATIVE
        # workload hits the HBM split cache, the measured accel factor
        # flips above 1, and optional scheduling STARVES the CPU pool
        # mid-job once pending < accel x tpuCapacity x trackers
        # (JobQueueTaskScheduler.java:290-327) — the convergence clause:
        # the assignment tail goes all-TPU
        run_and_profile(c, conf, "kmeans_round1")
        run_and_profile(c, conf, "kmeans_round2", out_suffix="-r2")
        # round 3 under the implemented f(x,y) minimizer
        # (JobQueueTaskScheduler.java:181-219 as mode=minimize): with
        # t_cpu >> t_tpu the optimum puts (nearly) everything on the
        # accelerator — the majority-TPU placement row
        conf.set("tpumr.scheduler.mode", "minimize")
        run_and_profile(c, conf, "kmeans_minimize", out_suffix="-r3")
        conf.set("tpumr.scheduler.mode", "shirahata")

        conf = c.create_job_conf()
        conf.set_job_name("hybrid-matmul")
        conf.set_input_paths(f"file://{work}/a.npy")
        conf.set_output_path(f"file://{work}/out-mm")
        conf.set_input_format(DenseInputFormat)
        conf.set("tpumr.dense.split.rows", n_mm // 4)
        conf.set("tpumr.matmul.b", f"file://{work}/b.npy")
        conf.set_map_kernel("matmul-block")
        conf.set_output_format(SequenceFileOutputFormat)
        conf.set_num_reduce_tasks(0)
        run_and_profile(c, conf, "matmul")


# ----------------------------------------------------- phase orchestration
#
# Every phase runs in its OWN subprocess, sequentially. Rationale
# (learned the hard way on this harness):
#  * the tunneled TPU is EXCLUSIVE — a second process cannot initialize
#    the backend while another holds it, so fresh-process measurements
#    (terasort_fresh) are only possible when the orchestrator itself
#    never touches the device;
#  * a wedged tunnel blocks inside an XLA call where no Python-level
#    timeout can preempt it — only a process boundary lets the run
#    continue past a hung phase instead of sinking the whole artifact;
#  * rows are written to bench_details.json INCREMENTALLY after every
#    phase (plus a write-through spill inside each phase), so even a
#    kill -9 of everything leaves the completed rows on disk.

#: (name, fn, device policy, full-scale timeout seconds). Policy:
#: "required" — skip when the backend is unavailable; "optional" — run
#: with whatever backend is up (fn handles TPU_OK internally);
#: "never" — pure host phase, always pinned to the CPU backend.
#:
#: ORDER IS SCARCITY-AWARE, not conceptual: rounds 2–4 each lost the
#: tail of the capture window to a mid-run tunnel wedge, and the rows
#: that died were always the ones scheduled LAST. So the phases whose
#: device rows have the fewest committed artifacts run FIRST:
#:  1. kernels  — on-chip MFU rows, never captured on hardware; also the
#:     cheapest device phase (no cluster, no staging), so it converts
#:     tunnel-seconds into evidence at the best rate;
#:  2. chained  — device-output chaining, never captured;
#:  3. hybrid   — the mid-job CPU→TPU convergence tail, never captured;
#:  4. terasort → terasort_fresh — fresh-process row never captured;
#:     the pair stays adjacent because terasort_fresh replays THIS
#:     run's shared dir + compile cache (see plan_resume);
#:  5. kmeans/pi/matmul/wordcount — device rows already committed in
#:     misc/bench_device_r{2,4}.json; re-measuring them is valuable but
#:     never at the cost of a never-captured row;
#:  6. codecs — pure host, immune to wedges, safely last.
PHASES: list = [
    ("kernels", bench_kernels, "required", 2400),
    ("chained", bench_chained, "required", 1800),
    ("hybrid", bench_hybrid, "required", 5400),
    ("terasort", bench_terasort, "optional", 2700),
    ("terasort_fresh", bench_terasort_fresh, "required", 1500),
    ("kmeans", bench_kmeans, "optional", 5400),
    ("kmeans_pipeline", bench_kmeans_pipeline, "never", 1800),
    ("straggler", bench_straggler, "never", 900),
    ("pi", bench_pi, "optional", 1200),
    ("matmul", bench_matmul, "optional", 1800),
    ("wordcount", bench_wordcount, "optional", 900),
    ("codecs", bench_codecs, "never", 600),
]


#: phase -> key that only exists when its DEVICE rows were captured;
#: --resume re-runs a "clean" phase whose device story is missing (it
#: completed host-only under an earlier wedge) once TPU is back
DEVICE_SENTINEL = {
    "kmeans": "kmeans_tpu_warm_job_s", "pi": "pi_tpu_job_s",
    "matmul": "matmul_tpu_job_s", "terasort": "terasort_device_job_s",
    "terasort_fresh": "terasort_device_fresh_process_cached_s",
    "kernels": "kernel_matmul_bf16_onchip_s",
    "chained": "chained_consumer_job_s",
    "hybrid": "hybrid_kmeans_round2_placement_seq",
}

_FRESH_KEY = "terasort_device_fresh_process_cached_s"
_ROW_PREFIX = {"codecs": "codec_", "kernels": "kernel_",
               "terasort_fresh": _FRESH_KEY}


def phase_owns(name: str, key: str) -> bool:
    """Row-ownership predicate per phase (keys are prefix-named; the
    overlaps are the terasort/terasort_fresh and
    kmeans/kmeans_pipeline pairs)."""
    if name == "terasort":
        return key.startswith("terasort_") and key != _FRESH_KEY
    if name == "kmeans":
        return key.startswith("kmeans_") \
            and not key.startswith("kmeans_pipeline_")
    return key.startswith(_ROW_PREFIX.get(name, name + "_"))


def phase_all_keys(name: str, rows: dict) -> "list[str]":
    """Every key in ``rows`` belonging to one phase: its data rows plus
    the orchestration meta keys. The ONE list both invalidation
    (plan_resume) and the forced-pair restore use — a meta key added to
    one but not the other would make them asymmetric."""
    meta = (f"bench_{name}", f"phase_{name}_s", f"phase_{name}_backend")
    return [k for k in rows if phase_owns(name, k) or k in meta]


def phase_done(prior: dict, name: str, device: str, tpu_ok: bool,
               backend: "str | None" = None) -> bool:
    """Did a prior run capture this phase completely (for --resume)?
    ``backend`` is THIS run's probed backend name."""
    if f"phase_{name}_s" not in prior or f"bench_{name}" in prior:
        return False              # never ran, or ran and failed
    stamp = prior.get(f"phase_{name}_backend")
    if tpu_ok and device != "never" and backend is not None \
            and stamp is not None and stamp != backend:
        # measured on a DIFFERENT backend (host-only fallback under a
        # wedge); the device is back — re-measure (covers phases with
        # no device-only row key, e.g. wordcount)
        return False
    sentinel = DEVICE_SENTINEL.get(name)
    if tpu_ok and device != "never" and sentinel is not None:
        val = prior.get(sentinel)
        if val is None or (isinstance(val, str)
                           and val.split(":")[0] in ("skipped",
                                                     "failed")):
            # the device story wasn't captured (phase ran host-only
            # under a wedge, or left a marker) — re-run now that the
            # device is back
            return False
    return True


def plan_resume(prior: dict, tpu_ok: bool, resume: bool, rows: dict,
                backend: "str | None" = None) -> "tuple[set, set, dict]":
    """Decide which phases run, and invalidate their prior rows.

    Returns ``(rerun, forced, invalidated)``. terasort and
    terasort_fresh re-run as a PAIR when the device is up: a re-run
    terasort invalidates the fresh-process row (it measures THIS run's
    compile cache + gen data), and a re-run terasort_fresh without its
    terasort would find a brand-new empty shared dir and converge to
    "skipped: no data" on every resume. ``forced`` holds the phases
    added ONLY by that pairing — if the device dies mid-loop before
    they run, the caller restores their rows from ``invalidated``
    rather than re-measuring host-only. Invalidation happens UP FRONT,
    not lazily per-iteration: a kill between a forced pair's first and
    second member must not leave the second's stale rows looking clean
    to the next resume; partway kills must never merge two attempts'
    measurements silently.
    """
    rerun = {name for name, _, device, _ in PHASES
             if not (resume and phase_done(prior, name, device, tpu_ok,
                                           backend))}
    forced: set = set()
    if tpu_ok and rerun & {"terasort", "terasort_fresh"}:
        forced = {"terasort", "terasort_fresh"} - rerun
        rerun |= forced
    invalidated: dict = {}
    if resume:
        for name in rerun:
            for k in phase_all_keys(name, rows):
                invalidated[k] = rows.pop(k)
    return rerun, forced, invalidated


def resume_context(prior: dict) -> dict:
    """The context a prior artifact's rows were measured under. For
    artifacts that predate context stamping, synthesize from what they
    recorded — the probe's backend, and the kmeans workload size (small
    pins 2M points) when a kmeans row exists; unknown scale must read
    as a MISMATCH (assuming the current scale would let small-scale
    rows merge into a full-scale run relabeled)."""
    ctx = prior.pop("bench_context", None)
    if ctx is not None:
        return ctx
    n_prior = prior.get("kmeans_n_points")
    import platform
    return {"backend": prior.get("backend_probe", {}).get("backend"),
            "small": (n_prior == 2_000_000) if n_prior else "unknown",
            # legacy artifacts carry no host stamp; trust them as LOCAL
            # (the resume restamps, so artifacts that travel in a git
            # clone mismatch on every other machine thereafter)
            "host": platform.node()}


def _atomic_json_dump(obj: dict, path: str, **kw) -> None:
    """tmp-file + rename: a SIGKILL mid-write must never leave truncated
    JSON at ``path`` — these files exist precisely to survive kills."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, **kw)
    os.replace(tmp, path)


class _SpillDict(dict):
    """Phase-side rows dict that writes itself through to a JSON side
    file on every insertion, so a phase killed mid-flight still leaves
    the rows it HAD captured for the orchestrator to merge."""

    def __init__(self, path: str):
        super().__init__()
        self._path = path

    def __setitem__(self, k, v):  # noqa: ANN001
        super().__setitem__(k, v)
        try:
            _atomic_json_dump(dict(self), self._path)
        except OSError:
            pass


def run_phase_child(name: str) -> int:
    """Entry for ``bench.py --phase NAME``: run one phase in this
    process (which owns the device for its lifetime) and hand rows back
    on stdout."""
    global TPU_OK
    env_ok = os.environ.get("BENCH_TPU_OK")
    entry = next((p for p in PHASES if p[0] == name), None)
    if entry is None:
        log(f"unknown phase: {name} (have: {[p[0] for p in PHASES]})")
        return 2
    _, fn, device, budget_s = entry
    # Wedge diagnostics: when a device op hangs (tunnel wedge — observed
    # live in round 4: main thread futex-parked under jax, tokio
    # transport idle in epoll, zero CPU), the orchestrator's kill leaves
    # no record of WHERE. Dump every thread's Python stack to stderr
    # shortly before the phase budget expires so the artifact pins the
    # hung frame, and register SIGUSR1 so an operator can poke a live
    # stack out of a wedged phase without killing it.
    import faulthandler
    import signal as _signal
    # chain=False: the default SIGUSR1 disposition is process death —
    # a live-poke diagnostic must dump and keep the phase running
    faulthandler.register(_signal.SIGUSR1, all_threads=True, chain=False)
    # dump strictly BEFORE the orchestrator's kill lands, whatever the
    # effective timeout (tiny-mult smoke runs included); a completed
    # phase cancels the timer, so only still-running phases ever dump.
    # The orchestrator exports its computed deadline; the formula below
    # is only for standalone `--phase` invocations.
    _eff = os.environ.get("BENCH_PHASE_BUDGET_S")
    if _eff is not None:
        _eff = float(_eff)
    else:
        _mult = float(os.environ.get("BENCH_PHASE_TIMEOUT_MULT", "1.0"))
        if SMALL:  # mirror the orchestrator's SMALL-mode reduction
            budget_s = max(120, budget_s // 6)
        _eff = budget_s * _mult
    faulthandler.dump_traceback_later(
        max(5.0, min(_eff - 30.0, _eff * 0.9)), exit=False)
    # standalone invocation (no orchestrator env): probe for ourselves —
    # then settle, because our own backend init follows the probe
    # child's exit into the same tunnel-session-release race the
    # orchestrator settles for. Only for a real tunneled device: cpu
    # backends and host-only phases have no session to settle (mirrors
    # the orchestrator's settle gating).
    if env_ok is not None:
        TPU_OK = env_ok == "1"
    else:
        probe_rows: dict = {}
        TPU_OK = probe_backend(probe_rows)
        if (TPU_OK and device != "never"
                and probe_rows.get("backend_probe", {}).get("backend")
                != "cpu"):
            time.sleep(float(os.environ.get("BENCH_PHASE_SETTLE", "15")))
    import jax
    if not TPU_OK or device == "never":
        jax.config.update("jax_platforms", "cpu")
    elif os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if TPU_OK and device != "never":
        # initialize the backend EAGERLY with a visible marker: when a
        # phase hangs with no output, the absence of this line pins the
        # hang on backend init (tunnel-session release race) rather
        # than on the phase's own work — round-4 smoke burned 300 s
        # being unable to tell the two apart
        t_init = time.time()
        devs = jax.devices()
        log(f"[{name}] backend ready: {devs[0].device_kind} x{len(devs)} "
            f"in {time.time() - t_init:.1f}s")
    spill = os.environ.get("BENCH_ROWS_SPILL")
    rows: dict = _SpillDict(spill) if spill else {}
    # stamp which backend measured this phase: phases without a
    # device-only row key (wordcount) would otherwise pass phase_done
    # forever after a host-only run under a wedge — cpu numbers wearing
    # the artifact's tpu label
    rows[f"phase_{name}_backend"] = jax.default_backend()
    t0 = time.time()
    failed = False
    try:
        fn(rows)
    except Exception as e:  # noqa: BLE001 — rows are best-effort
        failed = True
        log(f"[{name}] FAILED: {type(e).__name__}: {e}")
        import traceback
        traceback.print_exc(file=sys.stderr)
        rows[f"bench_{name}"] = f"failed: {type(e).__name__}: {e}"
    faulthandler.cancel_dump_traceback_later()
    log(f"[timing] {name}: {time.time() - t0:.1f}s")
    print("PHASE_ROWS " + json.dumps(rows), flush=True)
    # rc=3 tells the orchestrator "rows are good but the phase FAILED" —
    # it must re-probe the backend before sinking hours into later
    # device phases against a possibly-wedged tunnel
    return 3 if failed else 0


#: the detail artifact — written incrementally by the orchestrator and
#: read back by --resume; one constant so the two can never diverge
DETAILS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "bench_details.json")


def _dump(rows: dict) -> None:
    _atomic_json_dump(rows, DETAILS_PATH, indent=2, sort_keys=True)


def _bench_round() -> int:
    """Current build round: the driver writes BENCH_r{N}.json at the END
    of round N, so during round N the newest on-disk artifact is N−1.
    TPUMR_BENCH_ROUND overrides for out-of-band runs."""
    env = os.environ.get("TPUMR_BENCH_ROUND")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    import glob
    import re
    here = os.path.dirname(os.path.abspath(__file__))
    ns = [int(m.group(1))
          for p in glob.glob(os.path.join(here, "BENCH_r*.json"))
          for m in [re.search(r"BENCH_r0*(\d+)\.json$", p)] if m]
    return max(ns) + 1 if ns else 1


def _archive_device_capture(rows: dict) -> None:
    """Immutable per-round device artifact (VERDICT r4 Weak #3): any run
    that measured on a real device backend also MERGES its rows into
    ``misc/bench_device_r<N>.json``, which host-only runs never touch —
    so a later wedged-tunnel run overwriting bench_details.json can no
    longer erase a round's device evidence (round 4 lost its in-tree
    capture exactly that way; it survived only at git 949e5ed).
    BASELINE.md cites these files as the primary artifacts."""
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(here, "misc",
                        f"bench_device_r{_bench_round()}.json")
    merged: dict = {}
    try:
        with open(path) as f:
            merged = json.load(f)
    except (OSError, ValueError):
        pass
    incoming = {k: v for k, v in rows.items()
                if k != "prior_device_capture"}
    for name, _fn, _dev, _t in PHASES:
        # a phase that failed/stalled in an earlier run of this round but
        # completed now (phase timing present, no failure marker) must
        # not keep wearing the archived failure marker...
        if f"phase_{name}_s" in rows and f"bench_{name}" not in rows:
            merged.pop(f"bench_{name}", None)
        # ...and the converse: a later wedged run of the SAME round that
        # never reached this phase (skip/fail marker, no timing) must
        # not stamp its marker over an earlier run's good archived rows
        archived_good = (f"phase_{name}_s" in merged
                         and f"bench_{name}" not in merged)
        marker_only = (f"bench_{name}" in incoming
                       and f"phase_{name}_s" not in incoming)
        if archived_good and marker_only:
            incoming.pop(f"bench_{name}")
    merged.update(incoming)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        _atomic_json_dump(merged, path, indent=2, sort_keys=True)
    except OSError as e:  # archive failure must never kill the bench
        log(f"device-capture archive failed: {e}")


def _tree_cpu_s(root_pid: int) -> float:
    """Total CPU seconds (utime+stime) of ``root_pid`` and every LIVE
    descendant — by parent chain, not process group, because mini-cluster
    task children run under ``start_new_session`` (their own pgid) and a
    pgroup scan would miss exactly the processes doing the work. The
    wedge signature this feeds (observed live in round 4): main thread
    futex-parked under jax, transport idle in epoll, ZERO CPU — while a
    slow-but-healthy phase burns host CPU continuously. /proc scan; comm
    may contain spaces/parens, so fields resume after the LAST ')'.
    Exited descendants' CPU vanishes from the sum — callers must treat a
    decrease as a baseline reset, not negative progress."""
    tick_hz = os.sysconf("SC_CLK_TCK")
    info: dict = {}      # pid -> (ppid, cpu_s)
    try:
        pids = os.listdir("/proc")
    except OSError:
        return 0.0
    for pid in pids:
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/stat") as f:
                fields = f.read().rsplit(")", 1)[-1].split()
        except (OSError, IndexError):
            continue
        # fields[0]=state [1]=ppid [11]=utime [12]=stime
        if len(fields) > 12:
            info[int(pid)] = (int(fields[1]),
                              (int(fields[11]) + int(fields[12]))
                              / tick_hz)
    children: dict = {}
    for pid, (ppid, _cpu) in info.items():
        children.setdefault(ppid, []).append(pid)
    total, stack = 0.0, [root_pid]
    while stack:
        p = stack.pop()
        if p in info:
            total += info[p][1]
        stack.extend(children.get(p, ()))
    return total


def run_phase_subprocess(name: str, timeout_s: float, rows: dict,
                         stall_watch: bool = False) -> bool:
    """Run one phase in its own process group; merge its rows. Returns
    False when the phase timed out, stalled, or crashed (spilled rows
    are still merged).

    ``stall_watch`` (device phases on a tunneled backend only) arms the
    wedge watchdog: rounds 2–4 each lost a capture window to a tunnel
    wedge that parked a phase inside an XLA call, where only the FULL
    phase budget (2700 s at terasort in r4) eventually freed the run.
    The watchdog ends that: a phase showing no sign of life for
    ``BENCH_STALL_WINDOW_S`` (default 240 s) is killed early and marked
    stalled, so a wedge costs minutes, not the round's remaining tunnel
    life. "Sign of life" is any of: a completed device transfer
    (``tpumr.utils.progress`` ticks the progress file on every
    device_put/device_get), a spilled row, or real CPU burn (≥5% of the
    window across the phase's whole process group — a wedged tree shows
    ~zero; a long single-op compute or host-side stretch shows ~100%)."""
    import signal

    spill = os.path.join(os.environ["BENCH_SHARED_DIR"],
                         f"rows-{name}.json")
    prog = os.path.join(os.environ["BENCH_SHARED_DIR"],
                        f"progress-{name}")
    for stale in (spill, prog):  # stale files from a previous run in a
        try:                     # reused shared dir must never read as
            os.unlink(stale)     # fresh measurements / fresh liveness
        except OSError:
            pass
    env = dict(os.environ, BENCH_TPU_OK="1" if TPU_OK else "0",
               BENCH_ROWS_SPILL=spill,
               TPUMR_DEVICE_PROGRESS_FILE=prog,
               # the effective kill deadline, so the child's wedge stack
               # dump can be scheduled strictly before it without
               # re-deriving (and drifting from) this computation
               BENCH_PHASE_BUDGET_S=str(timeout_s))

    def merge_spill() -> None:
        try:
            with open(spill) as f:
                rows.update(json.load(f))
        except (OSError, ValueError):
            pass

    def kill_phase(child: "subprocess.Popen", why: str) -> None:
        log(f"[{name}] {why} — SIGTERM, 30s grace, then SIGKILL")
        try:
            os.killpg(child.pid, signal.SIGTERM)
        except OSError:
            child.terminate()
        try:
            child.wait(timeout=30)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(child.pid, signal.SIGKILL)
            except OSError:
                child.kill()
            try:
                child.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass

    def newest_mtime() -> float:
        m = 0.0
        for p in (spill, prog):
            try:
                m = max(m, os.stat(p).st_mtime)
            except OSError:
                pass
        return m

    stall_window = float(os.environ.get("BENCH_STALL_WINDOW_S", "240"))
    t0 = time.time()
    with tempfile.TemporaryFile("w+") as out:
        # stderr inherits: phase logs stream live into the bench log
        child = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--phase", name],
            stdout=out, env=env, start_new_session=True)
        # liveness baseline: spawn counts — the child gets stall_window
        # to show its first sign of life (backend init IS covered: the
        # round-4 chained hang parked exactly there)
        last_live = t0
        prev_cpu = 0.0
        accrued_cpu = 0.0
        seen_mtime = 0.0
        while True:
            try:
                child.wait(timeout=min(5.0, max(0.5, timeout_s / 100)))
                break
            except subprocess.TimeoutExpired:
                pass
            now = time.time()
            if now - t0 >= timeout_s:
                kill_phase(child,
                           f"phase TIMEOUT after {timeout_s:.0f}s")
                merge_spill()
                rows[f"bench_{name}"] = \
                    f"failed: phase timeout {timeout_s:.0f}s"
                rows[f"phase_{name}_s"] = round(time.time() - t0, 1)
                return False
            if not stall_watch:
                continue
            # CPU accrues as per-SAMPLE deltas, clamped at 0: a task
            # child exiting between samples drops its total from the
            # tree sum, which must cost at most that one interval's
            # delta — an absolute-baseline scheme reset the whole
            # window's accrual on every child churn and could kill a
            # busy mini-cluster phase as "stalled"
            cpu = _tree_cpu_s(child.pid)
            accrued_cpu += max(0.0, cpu - prev_cpu)
            prev_cpu = cpu
            m = newest_mtime()
            if m > seen_mtime or accrued_cpu >= 0.05 * stall_window:
                seen_mtime = max(seen_mtime, m)
                last_live = now
                accrued_cpu = 0.0
            elif now - last_live >= stall_window:
                kill_phase(
                    child,
                    f"phase STALLED: no device transfer, no row, and "
                    f"<5% CPU for {stall_window:.0f}s (tunnel wedge)")
                merge_spill()
                rows[f"bench_{name}"] = (
                    f"failed: stalled {stall_window:.0f}s without "
                    f"progress (wedged tunnel)")
                rows[f"phase_{name}_s"] = round(time.time() - t0, 1)
                return False
        out.seek(0)
        stdout = out.read()
    rows[f"phase_{name}_s"] = round(time.time() - t0, 1)
    line = next((ln for ln in stdout.splitlines()
                 if ln.startswith("PHASE_ROWS ")), None)
    if line is not None:
        # rows travel back even when the phase failed (rc=3: fn raised
        # but captured rows; the failure marker rides in the rows). The
        # line itself may be truncated by a mid-write kill — fall back
        # to the spill file rather than crash the orchestrator.
        try:
            rows.update(json.loads(line[len("PHASE_ROWS "):]))
            return child.returncode == 0
        except ValueError:
            log(f"[{name}] PHASE_ROWS line unparseable (truncated by a "
                f"kill?) — merging spill file instead")
    merge_spill()
    rows[f"bench_{name}"] = (
        f"failed: phase exited rc={child.returncode}"
        f"{' without parseable rows' if line else ' without rows'}")
    return False


def main() -> None:
    global TPU_OK
    if len(sys.argv) >= 3 and sys.argv[1] == "--phase":
        sys.exit(run_phase_child(sys.argv[2]))

    # --resume: merge the existing bench_details.json and run ONLY the
    # phases that left no rows (or left a failure marker). The recovery
    # path after a mid-run tunnel wedge: the completed phases' rows are
    # kept as-is; a wedged phase re-runs once the tunnel heals. The
    # summary line is recomputed over the merged artifact either way.
    resume = "--resume" in sys.argv[1:]
    prior: dict = {}
    try:
        with open(DETAILS_PATH) as f:
            prior = json.load(f)
    except (OSError, ValueError) as e:
        if resume:
            log(f"--resume: no usable bench_details.json ({e}); "
                f"running everything")
    # stale orchestration markers must not survive into a merged
    # artifact (a re-probe decides availability afresh)
    for k in ("tpu_unavailable", "tpu_unavailable_after_phase"):
        prior.pop(k, None)
    # stash the previous capture's device story BEFORE this run
    # overwrites the artifact: if the tunnel is down for the whole run,
    # the host-only artifact still points at the last real device
    # measurement (clearly labeled as prior with the context it was
    # measured under, never merged as fresh). Chains across consecutive
    # wedged days via the nested prior_device_capture.
    prior_device: dict = {}
    if isinstance(prior.get("kmeans_tpu_warm_job_s"), (int, float)):
        prior_device = {
            k: prior[k] for k in
            ("kmeans_tpu_warm_job_s", "kmeans_cpu_batch_job_s",
             "kmeans_n_points", "bench_context") if k in prior}
        if "bench_context" not in prior_device:
            # pre-stamping artifact: label it honestly rather than
            # presenting unlabeled (possibly cross-host) numbers
            prior_device["bench_context"] = {
                "backend": prior.get("backend_probe", {}).get("backend"),
                "synthesized": True}
    elif isinstance(prior.get("prior_device_capture"), dict):
        prior_device = prior["prior_device_capture"]
    if not resume:
        prior = {}
    #: the context the prior rows were measured under; compared against
    #: THIS run after the probe — resuming a cpu-pinned or small-scale
    #: artifact on a real full-scale device run must re-measure, never
    #: relabel (cpu numbers wearing tpu labels)
    prior_ctx = resume_context(prior) if prior else None

    # fresh per-run persistent compilation cache: each phase's "cold"
    # rows stay true cold for their own shapes, while terasort_fresh
    # measures the production cold path (cache inherited across the
    # process boundary). setdefault: an operator-exported
    # TPUMR_JAX_CACHE_DIR is honored — but then the "cold" rows measure
    # cache-hit compiles, so only preset it deliberately.
    os.environ.setdefault("TPUMR_JAX_CACHE_DIR", tempfile.mkdtemp(
        prefix="tpumr-bench-jaxcache-"))
    os.environ.setdefault("BENCH_SHARED_DIR", tempfile.mkdtemp(
        prefix="tpumr-bench-shared-"))
    rows: dict = {}
    if resume and prior:
        # seed BEFORE the first _dump: the startup dump must never
        # replace the on-disk artifact with probe-only rows while the
        # prior measurements live only in this process's memory
        rows.update({k: v for k, v in prior.items()
                     if k != "backend_probe"})
    # probe in a SUBPROCESS before anything else: a wedged tunnel yields
    # a host-only partial artifact, never rc=1 with nothing
    TPU_OK = probe_backend(rows)
    backend_name = rows.get("backend_probe", {}).get(
        "backend", "unavailable") if TPU_OK else "unavailable"
    log(f"orchestrator: backend={backend_name} "
        f"scale={'small' if SMALL else 'full'}; one process per phase "
        f"(exclusive device, per-phase timeouts, incremental artifact)")
    import platform
    current_ctx = {"backend": backend_name if TPU_OK else None,
                   "small": SMALL, "host": platform.node()}
    if resume and prior:
        ctx = prior_ctx or {}
        # scale and host must always match; backend must match whenever
        # THIS run has one (with the device down, prior device rows are
        # kept — the re-run phases can only add host rows, which carry
        # no device labels to mislabel). The host check stops a
        # git-tracked artifact from another machine being merged into a
        # local run as if it were this machine's own interrupted state.
        if ctx.get("small") != SMALL \
                or ctx.get("host") != current_ctx["host"] or (
                TPU_OK and ctx.get("backend") != backend_name):
            log(f"--resume: prior artifact context {ctx} does not match "
                f"this run {current_ctx} — ignoring prior rows, "
                f"running everything")
            prior = {}
            rows = {k: v for k, v in rows.items()
                    if k in ("backend_probe", "tpu_unavailable")}
    # the artifact's context: the prior run's when its rows are kept
    # (a device-down resume stays labeled by the run that measured it)
    rows["bench_context"] = prior_ctx if (resume and prior) else current_ctx
    _dump(rows)
    mult = float(os.environ.get("BENCH_PHASE_TIMEOUT_MULT", "1.0"))
    settle_s = float(os.environ.get("BENCH_PHASE_SETTLE", "15"))
    # the settle exists for the tunneled device's async session release;
    # a CPU-pinned run has no tunnel to settle (or 30s-floor re-probe) for
    tunnel = rows.get("backend_probe", {}).get("backend") not in (None,
                                                                  "cpu")
    if not tunnel:
        settle_s = 0.0
    # the startup probe subprocess already touched the device, so the
    # FIRST device phase needs the settle too. Time-based, not
    # previous-phase-based: a short host-only phase between two device
    # phases must not cancel the settle.
    last_device_exit = time.time() if TPU_OK else 0.0

    rerun, forced, invalidated = plan_resume(prior, TPU_OK, resume, rows,
                                            backend_name)
    if resume and invalidated:
        _dump(rows)
    for name, _, device, timeout_s in PHASES:
        if name not in rerun:
            log(f"[{name}] --resume: rows present and clean — skipping")
            continue
        if name in forced and not TPU_OK:
            # this phase was dragged in ONLY by pair-forcing while the
            # device was up; the tunnel has since died mid-loop — put
            # its invalidated prior rows back rather than overwrite
            # good device measurements with a host-only re-measure
            rows.update({k: invalidated[k]
                         for k in phase_all_keys(name, invalidated)})
            _dump(rows)
            log(f"[{name}] device lost mid-resume — restored prior rows "
                f"instead of re-measuring host-only")
            continue
        if device == "required" and not TPU_OK:
            rows[f"bench_{name}"] = "skipped: tpu unavailable"
            log(f"[{name}] skipped: device required, backend unavailable")
            _dump(rows)
            continue
        if SMALL:
            timeout_s = max(120, timeout_s // 6)
        touches_device = TPU_OK and device != "never"
        remaining = settle_s - (time.time() - last_device_exit)
        if touches_device and last_device_exit and remaining > 0:
            # the tunneled TPU is exclusive and its server releases a
            # dead client's session asynchronously: a phase child that
            # begins backend init before the release lands can park in
            # init forever (the round-4 chained-phase hang). A short
            # settle between device phases sidesteps the race.
            log(f"[{name}] settling {remaining:.0f}s for tunnel session "
                f"release before next device phase")
            time.sleep(remaining)
        # the wedge watchdog arms only for device phases over a real
        # tunnel: host-pinned runs (CI, virtual-mesh) have no tunnel to
        # wedge, and "never" phases do pure host work by design
        ok = run_phase_subprocess(name, timeout_s * mult, rows,
                                  stall_watch=touches_device and tunnel)
        if touches_device:
            last_device_exit = time.time()
        _dump(rows)
        if tunnel:
            # archive incrementally: a driver-level kill mid-run must
            # not cost the already-captured device rows their immutable
            # per-round artifact
            _archive_device_capture(rows)
        if not ok and TPU_OK and device != "never":
            # the failed phase may have wedged the tunnel; a cheap
            # re-probe decides whether later device phases stand a chance.
            # Settle first (30 s floor even when the operator zeroed the
            # inter-phase settle) — probing into the just-killed child's
            # half-released session reads as wedged even when it isn't.
            if tunnel:
                time.sleep(max(settle_s, 30.0))
            if probe_backend({}, attempts=1, timeout_s=120.0):
                log(f"[{name}] failed but backend re-probe OK — continuing")
            else:
                TPU_OK = False
                rows["tpu_unavailable_after_phase"] = name
                log(f"[{name}] backend re-probe FAILED — skipping "
                    f"remaining device phases")
            _dump(rows)
    # safety net only: every mutation above already dumps, but a future
    # branch that forgets must not ship a stale artifact
    _dump(rows)
    if tunnel:
        _archive_device_capture(rows)
    log(f"detail rows -> bench_details.json: "
        f"{json.dumps(rows, sort_keys=True)}")

    n = rows.get("kmeans_n_points", 0)
    t_cpu = rows.get("kmeans_cpu_batch_job_s") or 0.0
    t_warm = rows.get("kmeans_tpu_warm_job_s") or 0.0
    if t_warm and t_cpu:
        if rows.pop("prior_device_capture", None) is not None:
            # a fresh device capture retires the prior-run pointer
            _dump(rows)
        print(json.dumps({
            "metric": f"kmeans {n / 1e6:.0f}M-pt full-job wall-clock, "
                      f"warm iterative round (tpu kernel vs vectorized "
                      f"cpu-only batch baseline; "
                      f"cold={rows.get('kmeans_tpu_cold_job_s')}s)",
            "value": round(t_warm, 3),
            "unit": "seconds/job",
            "vs_baseline": round(t_cpu / t_warm, 2),
        }))
    else:
        # partial artifact with an explicit marker — a wedged tunnel or
        # mid-run device failure stays diagnosable
        why = ("TPU BACKEND UNAVAILABLE — host-only partial capture"
               if not TPU_OK else
               "device kmeans did not complete — partial capture")
        summary = {
            "metric": f"kmeans {n / 1e6:.0f}M-pt cpu-batch full-job "
                      f"wall-clock ({why})",
            "value": round(t_cpu, 3),
            "unit": "seconds/job",
            "vs_baseline": 0.0,
            "tpu_unavailable": not TPU_OK,
        }
        if prior_device:
            # the last real device capture, labeled as such — a wedged
            # capture day must not erase the pointer to measured history
            summary["prior_device_capture"] = prior_device
            rows["prior_device_capture"] = prior_device
            _dump(rows)
        print(json.dumps(summary))


if __name__ == "__main__":
    main()
