"""Microbenchmark: shuffle merge engine vs the flat merge.

Measures the reduce-side merge data plane in isolation (no cluster, no
kernels — pure host path), on the wide-shuffle shape the critical-path
tool shows dominating warm wall-clock:

- ``merge_throughput``  — k-way merge records/sec over W sorted
  segments: the seed's flat ``heapq.merge(..., key=lambda kv:
  sort_key(kv[0]))`` vs the engine's raw-key fast path
  (``ifile.merge_sorted``: itemgetter key, dedicated two-way loop).
- ``two_way``           — the dominant map-side shape (two runs).
- ``bounded_fanin``     — multi-pass merge at ``io.sort.factor`` over a
  segment count far above the factor: the engine pays intermediate disk
  passes to bound fan-in; the row records the cost so the bound is an
  informed trade, not a hidden tax.
- ``copier_engine`` / ``copier_flat`` — a ShuffleCopier run over W
  in-memory map outputs with a RAM budget ≪ total bytes, background
  in-memory merging ON vs OFF, measuring copy+merge-drain wall-clock
  and how many segments fell to per-segment disk spills.

Output contract (same shape as ``bench.py``): ONE JSON line on stdout
  {"metric", "value", "unit", "vs_baseline"}
with vs_baseline = engine merge throughput / flat merge throughput on
the wide-shuffle merge. Every other row goes to stderr and to
``bench_shuffle.json``. env BENCH_SCALE=small (or --smoke) shrinks the
workload for CI smoke runs.
"""

from __future__ import annotations

import heapq
import json
import os
import shutil
import sys
import tempfile
import time


def log(*a: object) -> None:
    print(*a, file=sys.stderr, flush=True)


SMALL = os.environ.get("BENCH_SCALE") == "small" or "--smoke" in sys.argv

#: wide-shuffle shape: W map-output segments × R records each
W = 8 if SMALL else 64
R = 2_000 if SMALL else 30_000


def make_segments(w: int, r: int) -> "list[list[tuple[bytes, bytes]]]":
    """W sorted segments with interleaved (shared-prefix) keys — the
    wordcount-like shape where equal-key tiebreaks actually fire."""
    import random
    rng = random.Random(0)
    segs = []
    for _ in range(w):
        seg = sorted((b"k%08d" % rng.randrange(r * 4), b"v" * 10)
                     for _ in range(r))
        segs.append(seg)
    return segs


def drain(it) -> int:
    n = 0
    for _ in it:
        n += 1
    return n


def timed(fn) -> "tuple[float, int]":
    t0 = time.perf_counter()
    n = fn()
    return time.perf_counter() - t0, n


def bench_merge_throughput(rows: dict) -> "tuple[float, float]":
    from tpumr.io import ifile

    segs = make_segments(W, R)
    total = W * R

    def flat() -> int:
        # the seed path: one lazy heap merge over every segment, with a
        # Python-level key-fn call (plus closure frame) per comparison
        sk = lambda k: k  # noqa: E731 — the RawComparator identity seam
        return drain(heapq.merge(*segs, key=lambda kv: sk(kv[0])))

    def engine() -> int:
        # the background merger's kernel: budget-bounded batches are
        # fully resident, so Timsort galloping merges the runs at C speed
        return drain(ifile.merge_sorted_inmem(segs, lambda k: k))

    def engine_lazy() -> int:
        # the engine's lazy path (final merges): raw-key itemgetter key
        return drain(ifile.merge_sorted(segs, lambda k: k))

    # alternate and keep the best of 3: same allocator state for both
    t_flat = min(timed(flat)[0] for _ in range(3))
    t_eng = min(timed(engine)[0] for _ in range(3))
    t_lazy = min(timed(engine_lazy)[0] for _ in range(3))
    r_flat, r_eng = total / t_flat, total / t_eng
    rows["merge_segments"] = W
    rows["merge_records"] = total
    rows["merge_flat_rec_per_s"] = round(r_flat)
    rows["merge_engine_rec_per_s"] = round(r_eng)
    rows["merge_engine_lazy_rec_per_s"] = round(total / t_lazy)
    rows["merge_engine_speedup"] = round(r_eng / r_flat, 3)
    log(f"[merge] {W}-way x {R} records: flat {r_flat / 1e6:.2f}M rec/s, "
        f"engine in-mem {r_eng / 1e6:.2f}M rec/s "
        f"({r_eng / r_flat:.2f}x), engine lazy "
        f"{total / t_lazy / 1e6:.2f}M rec/s")

    segs2 = make_segments(2, total // 2)
    t2_flat = min(timed(lambda: drain(
        heapq.merge(*segs2, key=lambda kv: kv[0])))[0] for _ in range(3))
    t2_eng = min(timed(lambda: drain(
        ifile.merge_sorted(segs2, lambda k: k)))[0] for _ in range(3))
    rows["two_way_flat_rec_per_s"] = round(total / t2_flat)
    rows["two_way_engine_rec_per_s"] = round(total / t2_eng)
    log(f"[two-way] {total} records: flat {total / t2_flat / 1e6:.2f}M "
        f"rec/s, engine {total / t2_eng / 1e6:.2f}M rec/s -> "
        f"{t2_flat / t2_eng:.2f}x")
    return r_eng, r_flat


def bench_bounded_fanin(rows: dict) -> None:
    from tpumr.io import merger as merge_engine

    factor = 10
    segs = make_segments(W, R // 2)
    total = W * (R // 2)
    run_dir = tempfile.mkdtemp(prefix="bench-shuffle-merge-")
    try:
        bm = merge_engine.BoundedMerge(segs, None, factor,
                                       run_dir=run_dir)
        t, n = timed(lambda: drain(bm))
        assert n == total, f"bounded merge lost records: {n} != {total}"
        rows["fanin_factor"] = factor
        rows["fanin_passes"] = bm.passes
        rows["fanin_max_fan_in"] = bm.max_fan_in
        rows["fanin_rec_per_s"] = round(total / t)
        bm.close()
        log(f"[fan-in] {W} runs at factor {factor}: {bm.passes} passes, "
            f"max fan-in {bm.max_fan_in}, {total / t / 1e6:.2f}M rec/s "
            f"(the bounded-memory trade)")
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)


class _SpillSource:
    """ChunkFetch over in-memory spill files (the test double of the
    tracker's get_map_output_chunk), with a small per-chunk hold
    emulating tracker RPC latency — the window the background merger
    exists to overlap."""

    chunk_bytes = 64 * 1024

    def __init__(self, spills, latency_s: float = 0.0005) -> None:
        self.spills = spills
        self.latency_s = latency_s

    def __call__(self, map_index: int, partition: int, offset: int) -> dict:
        if self.latency_s:
            time.sleep(self.latency_s)
        data, index = self.spills[map_index]
        off, raw_len, part_len = index["partitions"][partition]
        payload = data[off + 4: off + part_len]
        return {"data": payload[offset: offset + self.chunk_bytes],
                "total": len(payload), "raw": raw_len,
                "codec": index.get("codec", "none")}


def bench_copier(rows: dict) -> "tuple[float, float]":
    """The wide-shuffle microbench proper: copy + merge-drain wall-clock
    with the engine (background in-memory merges + bounded fan-in + raw
    fast path) vs the flat seed path (no background merging, one
    heapq.merge with a key-fn over every segment)."""
    import io as _io

    from tpumr.io import ifile, merger as merge_engine
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.shuffle_copier import ShuffleCopier

    w = 12 if SMALL else max(40, W // 2)
    r = R // 2
    spills = []
    for m in range(w):
        buf = _io.BytesIO()
        wtr = ifile.Writer(buf, codec="none")
        wtr.start_partition()
        for kb, vb in sorted((b"k%08d" % ((i * 37 + m) % (r * 4)),
                              b"v" * 10) for i in range(r)):
            wtr.append_raw(kb, vb)
        wtr.end_partition()
        spills.append((buf.getvalue(), wtr.close()))
    total = w * r
    seg_bytes = spills[0][1]["partitions"][0][1]
    # budget ~6 segments (one segment is < the 25% max_single cap, so
    # segments CAN land in memory) against w ≫ 6 total: without the
    # background merger most of the shuffle falls to per-segment disk
    # spills once the budget fills
    ram_mb = seg_bytes * 6.2 / (0.70 * 1024 * 1024)

    def run(enabled: bool) -> "tuple[float, float, ShuffleCopier]":
        from tpumr.mapred.api import RawComparator
        conf = JobConf()
        conf.set_output_key_comparator_class(RawComparator)
        conf.set("tpumr.shuffle.ram.mb", ram_mb)
        conf.set("tpumr.shuffle.merge.enabled", enabled)
        spill_dir = tempfile.mkdtemp(prefix="bench-shuffle-copy-")
        copier = ShuffleCopier(conf, _SpillSource(spills), w, 0, spill_dir)
        t0 = time.perf_counter()
        segs = copier.copy_all()
        t_copy = time.perf_counter() - t0
        t0 = time.perf_counter()
        if enabled:
            bm = merge_engine.BoundedMerge(segs, None, 10,
                                           run_dir=spill_dir)
            n = drain(bm)
        else:
            sk = lambda k: k  # noqa: E731 — the seed's flat merge
            n = drain(heapq.merge(*segs, key=lambda kv: sk(kv[0])))
        t_merge = time.perf_counter() - t0
        assert n == total, f"copier merge lost records: {n} != {total}"
        if enabled:
            bm.close()
        for s in segs:
            s.close()
        shutil.rmtree(spill_dir, ignore_errors=True)
        return t_copy, t_merge, copier

    t_copy_e, t_merge_e, c_eng = min((run(True) for _ in range(2)),
                                     key=lambda p: p[0] + p[1])
    t_copy_f, t_merge_f, c_flat = min((run(False) for _ in range(2)),
                                      key=lambda p: p[0] + p[1])
    t_eng, t_flat = t_copy_e + t_merge_e, t_copy_f + t_merge_f
    rows["copier_maps"] = w
    rows["copier_engine_copy_s"] = round(t_copy_e, 4)
    rows["copier_engine_merge_s"] = round(t_merge_e, 4)
    rows["copier_flat_copy_s"] = round(t_copy_f, 4)
    rows["copier_flat_merge_s"] = round(t_merge_f, 4)
    rows["copier_engine_s"] = round(t_eng, 4)
    rows["copier_flat_s"] = round(t_flat, 4)
    rows["copier_engine_speedup"] = round(t_flat / t_eng, 3)
    rows["copier_merge_phase_speedup"] = round(t_merge_f / t_merge_e, 3)
    rows["copier_engine_inmem_merges"] = c_eng.inmem_merges
    rows["copier_engine_segments_disk"] = c_eng.spilled_to_disk
    rows["copier_flat_segments_disk"] = c_flat.spilled_to_disk
    log(f"[copier] {w} maps, budget ~6 segments: engine copy "
        f"{t_copy_e:.3f}s + merge {t_merge_e:.3f}s "
        f"({c_eng.inmem_merges} in-mem merges, "
        f"{c_eng.spilled_to_disk} disk segments) vs flat copy "
        f"{t_copy_f:.3f}s + merge {t_merge_f:.3f}s "
        f"({c_flat.spilled_to_disk} disk segments) -> end-to-end "
        f"{t_flat / t_eng:.2f}x, merge_reduce phase "
        f"{t_merge_f / t_merge_e:.2f}x")
    return t_eng, t_flat


def main() -> None:
    rows: dict = {}
    r_eng, r_flat = bench_merge_throughput(rows)
    bench_bounded_fanin(rows)
    bench_copier(rows)
    with open("bench_shuffle.json", "w") as f:
        json.dump(rows, f, sort_keys=True, indent=1)
    log(f"detail rows -> bench_shuffle.json: "
        f"{json.dumps(rows, sort_keys=True)}")
    print(json.dumps({
        "metric": f"wide-shuffle merge throughput, {W} segments x {R} "
                  f"records: merge engine (in-memory Timsort-galloping "
                  f"merge, the background merger's kernel) vs the flat "
                  f"key-fn heap merge over all segments",
        "value": round(r_eng),
        "unit": "records/sec",
        "vs_baseline": round(r_eng / r_flat, 2),
    }))


if __name__ == "__main__":
    main()
