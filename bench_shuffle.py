"""Microbenchmark: shuffle merge engine vs the flat merge.

Measures the reduce-side merge data plane in isolation (no cluster, no
kernels — pure host path), on the wide-shuffle shape the critical-path
tool shows dominating warm wall-clock:

- ``merge_throughput``  — k-way merge records/sec over W sorted
  segments: the seed's flat ``heapq.merge(..., key=lambda kv:
  sort_key(kv[0]))`` vs the engine's raw-key fast path
  (``ifile.merge_sorted``: itemgetter key, dedicated two-way loop).
- ``two_way``           — the dominant map-side shape (two runs).
- ``bounded_fanin``     — multi-pass merge at ``io.sort.factor`` over a
  segment count far above the factor: the engine pays intermediate disk
  passes to bound fan-in; the row records the cost so the bound is an
  informed trade, not a hidden tax.
- ``copier_engine`` / ``copier_flat`` — a ShuffleCopier run over W
  in-memory map outputs with a RAM budget ≪ total bytes, background
  in-memory merging ON vs OFF, measuring copy+merge-drain wall-clock
  and how many segments fell to per-segment disk spills.
- ``wire_*`` — the copy path over a REAL reactor RpcServer on
  loopback: pipelined chunk streams vs one-at-a-time
  (``wire_pipeline_speedup``), a wide job's batched multi-segment
  fetches vs per-segment RPCs under a per-RPC hold
  (``wire_batch_speedup`` — the small-segment regime where roundtrip
  overhead dominates), and tlz wire compression
  (``wire_compress_ratio``).

When a previous ``bench_shuffle.json`` exists, a ``[vs prior]`` line
per headline metric goes to stderr before the file is overwritten.

Output contract (same shape as ``bench.py``): ONE JSON line on stdout
  {"metric", "value", "unit", "vs_baseline"}
with vs_baseline = engine merge throughput / flat merge throughput on
the wide-shuffle merge. Every other row goes to stderr and to
``bench_shuffle.json``. env BENCH_SCALE=small (or --smoke) shrinks the
workload for CI smoke runs.
"""

from __future__ import annotations

import heapq
import json
import os
import shutil
import sys
import tempfile
import time


def log(*a: object) -> None:
    print(*a, file=sys.stderr, flush=True)


SMALL = os.environ.get("BENCH_SCALE") == "small" or "--smoke" in sys.argv

#: wide-shuffle shape: W map-output segments × R records each
W = 8 if SMALL else 64
R = 2_000 if SMALL else 30_000

#: copier-row regime: per-chunk RPC hold emulating a remote shuffle
#: (64 KiB / 20 ms ≈ 3 MB/s per stream) and the in-memory budget in
#: segments — copy-dominated, the regime the copy path lives in
COPIER_LATENCY_S = 0.0 if SMALL else 0.02
COPIER_BUDGET_SEGS = 6.2


def make_segments(w: int, r: int) -> "list[list[tuple[bytes, bytes]]]":
    """W sorted segments with interleaved (shared-prefix) keys — the
    wordcount-like shape where equal-key tiebreaks actually fire."""
    import random
    rng = random.Random(0)
    segs = []
    for _ in range(w):
        seg = sorted((b"k%08d" % rng.randrange(r * 4), b"v" * 10)
                     for _ in range(r))
        segs.append(seg)
    return segs


def drain(it) -> int:
    n = 0
    for _ in it:
        n += 1
    return n


def timed(fn) -> "tuple[float, int]":
    t0 = time.perf_counter()
    n = fn()
    return time.perf_counter() - t0, n


def bench_merge_throughput(rows: dict) -> "tuple[float, float]":
    from tpumr.io import ifile

    segs = make_segments(W, R)
    total = W * R

    def flat() -> int:
        # the seed path: one lazy heap merge over every segment, with a
        # Python-level key-fn call (plus closure frame) per comparison
        sk = lambda k: k  # noqa: E731 — the RawComparator identity seam
        return drain(heapq.merge(*segs, key=lambda kv: sk(kv[0])))

    def engine() -> int:
        # the background merger's kernel: budget-bounded batches are
        # fully resident, so Timsort galloping merges the runs at C speed
        return drain(ifile.merge_sorted_inmem(segs, lambda k: k))

    def engine_lazy() -> int:
        # the engine's lazy path (final merges): raw-key itemgetter key
        return drain(ifile.merge_sorted(segs, lambda k: k))

    # alternate and keep the best of 3: same allocator state for both
    t_flat = min(timed(flat)[0] for _ in range(3))
    t_eng = min(timed(engine)[0] for _ in range(3))
    t_lazy = min(timed(engine_lazy)[0] for _ in range(3))
    r_flat, r_eng = total / t_flat, total / t_eng
    rows["merge_segments"] = W
    rows["merge_records"] = total
    rows["merge_flat_rec_per_s"] = round(r_flat)
    rows["merge_engine_rec_per_s"] = round(r_eng)
    rows["merge_engine_lazy_rec_per_s"] = round(total / t_lazy)
    rows["merge_engine_speedup"] = round(r_eng / r_flat, 3)
    log(f"[merge] {W}-way x {R} records: flat {r_flat / 1e6:.2f}M rec/s, "
        f"engine in-mem {r_eng / 1e6:.2f}M rec/s "
        f"({r_eng / r_flat:.2f}x), engine lazy "
        f"{total / t_lazy / 1e6:.2f}M rec/s")

    segs2 = make_segments(2, total // 2)
    t2_flat = min(timed(lambda: drain(
        heapq.merge(*segs2, key=lambda kv: kv[0])))[0] for _ in range(3))
    t2_eng = min(timed(lambda: drain(
        ifile.merge_sorted(segs2, lambda k: k)))[0] for _ in range(3))
    rows["two_way_flat_rec_per_s"] = round(total / t2_flat)
    rows["two_way_engine_rec_per_s"] = round(total / t2_eng)
    log(f"[two-way] {total} records: flat {total / t2_flat / 1e6:.2f}M "
        f"rec/s, engine {total / t2_eng / 1e6:.2f}M rec/s -> "
        f"{t2_flat / t2_eng:.2f}x")
    return r_eng, r_flat


def bench_bounded_fanin(rows: dict) -> None:
    from tpumr.io import merger as merge_engine

    factor = 10
    segs = make_segments(W, R // 2)
    total = W * (R // 2)
    run_dir = tempfile.mkdtemp(prefix="bench-shuffle-merge-")
    try:
        bm = merge_engine.BoundedMerge(segs, None, factor,
                                       run_dir=run_dir)
        t, n = timed(lambda: drain(bm))
        assert n == total, f"bounded merge lost records: {n} != {total}"
        rows["fanin_factor"] = factor
        rows["fanin_passes"] = bm.passes
        rows["fanin_max_fan_in"] = bm.max_fan_in
        rows["fanin_rec_per_s"] = round(total / t)
        bm.close()
        log(f"[fan-in] {W} runs at factor {factor}: {bm.passes} passes, "
            f"max fan-in {bm.max_fan_in}, {total / t / 1e6:.2f}M rec/s "
            f"(the bounded-memory trade)")
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)


class _SpillSource:
    """The wire half of the copier row: in-memory spill files served
    chunk-at-a-time with a per-request RTT hold. ``__call__`` is the
    seed's sequential fetch (one outstanding request, full RTT per
    chunk); ``fetch_chunks`` is the overhauled protocol — a
    depth-bounded window of concurrent requests whose holds overlap,
    exactly what the real pipelined ``call_begin`` window buys on a
    leased connection."""

    chunk_bytes = 64 * 1024
    pipeline_depth = 4

    def __init__(self, spills, latency_s: float = 0.0005) -> None:
        self.spills = spills
        self.latency_s = latency_s
        self._pool = None

    def _chunk(self, map_index: int, partition: int, offset: int) -> dict:
        data, index = self.spills[map_index]
        off, raw_len, part_len = index["partitions"][partition]
        payload = data[off + 4: off + part_len]
        return {"data": payload[offset: offset + self.chunk_bytes],
                "total": len(payload), "raw": raw_len,
                "codec": index.get("codec", "none")}

    def __call__(self, map_index: int, partition: int, offset: int) -> dict:
        if self.latency_s:
            time.sleep(self.latency_s)
        return self._chunk(map_index, partition, offset)

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def fetch_chunks(self, map_index: int, partition: int,
                     start: int = 0, total: "int | None" = None):
        from collections import deque
        first = self(map_index, partition, start)
        yield first
        offsets = iter(range(start + len(first["data"]), first["total"],
                             self.chunk_bytes))
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=16, thread_name_prefix="bench-wire")
        pending: "deque" = deque()
        for off in offsets:
            pending.append(
                self._pool.submit(self, map_index, partition, off))
            if len(pending) >= self.pipeline_depth:
                break
        while pending:
            out = pending.popleft().result()
            nxt = next(offsets, None)
            if nxt is not None:
                pending.append(
                    self._pool.submit(self, map_index, partition, nxt))
            yield out


class _SeqView:
    """The seed's wire interface: a plain 3-arg chunk callable with no
    ``fetch_chunks``, so the copier takes its legacy sequential path —
    one outstanding request, a full RTT hold per chunk."""

    def __init__(self, source: _SpillSource) -> None:
        self._source = source
        self.chunk_bytes = source.chunk_bytes

    def __call__(self, map_index: int, partition: int, offset: int) -> dict:
        return self._source(map_index, partition, offset)


def bench_copier(rows: dict) -> "tuple[float, float]":
    """The end-to-end copy+merge row: the overhauled shuffle engine
    against the seed it replaced. "Engine" is the full new path —
    pipelined ``fetch_chunks`` wire (RTT holds overlap inside a
    depth-bounded window), no-park landing, background in-memory AND
    disk-run merges, and ``io.sort.factor`` tuned per the ops guide so
    the final merge is one pass. "Flat" is the seed: one outstanding
    chunk request per segment (full RTT each) and a single unbounded
    ``heapq.merge`` with a key-fn over every landed segment.

    The row is COPY-DOMINATED: a 20 ms per-chunk RPC hold emulates a
    remote shuffle (64 KiB / 20 ms ≈ 3 MB/s per stream), the regime the
    copy path actually lives in. The engine's win is the overlap the
    pipelined wire buys plus whatever merging it hides inside the
    remaining waits."""
    import io as _io

    from tpumr.io import ifile, merger as merge_engine
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.shuffle_copier import ShuffleCopier

    w = 12 if SMALL else max(40, W // 2)
    r = R // 2
    lat = COPIER_LATENCY_S
    budget_segs = COPIER_BUDGET_SEGS
    spills = []
    for m in range(w):
        buf = _io.BytesIO()
        wtr = ifile.Writer(buf, codec="none")
        wtr.start_partition()
        for kb, vb in sorted((b"k%08d" % ((i * 37 + m) % (r * 4)),
                              b"v" * 10) for i in range(r)):
            wtr.append_raw(kb, vb)
        wtr.end_partition()
        spills.append((buf.getvalue(), wtr.close()))
    total = w * r
    seg_bytes = spills[0][1]["partitions"][0][1]
    # budget ≪ w segments (one segment is < the 25% max_single cap, so
    # segments CAN land in memory): without the background merger most
    # of the shuffle falls to per-segment disk spills once the budget
    # fills
    ram_mb = seg_bytes * budget_segs / (0.70 * 1024 * 1024)

    # the engine's merge fan-in, tuned for this width per the ops
    # guide (w + merged runs stay below it: the final merge is ONE
    # pass); the seed's flat merge is unbounded by construction
    factor = w + 16

    def run(enabled: bool) -> "tuple[float, float, ShuffleCopier]":
        from tpumr.mapred.api import RawComparator
        conf = JobConf()
        conf.set_output_key_comparator_class(RawComparator)
        conf.set("tpumr.shuffle.ram.mb", ram_mb)
        conf.set("tpumr.shuffle.merge.enabled", enabled)
        conf.set("io.sort.factor", factor)
        src = _SpillSource(spills, latency_s=lat)
        # the seed's wire is a plain 3-arg chunk callable — one
        # outstanding request, a full RTT hold per chunk; the engine
        # sees the full protocol (pipelined fetch_chunks)
        source = src if enabled else _SeqView(src)
        spill_dir = tempfile.mkdtemp(prefix="bench-shuffle-copy-")
        copier = ShuffleCopier(conf, source, w, 0, spill_dir)
        t0 = time.perf_counter()
        segs = copier.copy_all()
        t_copy = time.perf_counter() - t0
        t0 = time.perf_counter()
        if enabled:
            bm = merge_engine.BoundedMerge(segs, None, factor,
                                           run_dir=spill_dir)
            n = drain(bm)
        else:
            sk = lambda k: k  # noqa: E731 — the seed's flat merge
            n = drain(heapq.merge(*segs, key=lambda kv: sk(kv[0])))
        t_merge = time.perf_counter() - t0
        assert n == total, f"copier merge lost records: {n} != {total}"
        if enabled:
            bm.close()
        for s in segs:
            s.close()
        src.close()
        shutil.rmtree(spill_dir, ignore_errors=True)
        return t_copy, t_merge, copier

    t_copy_e, t_merge_e, c_eng = min((run(True) for _ in range(2)),
                                     key=lambda p: p[0] + p[1])
    t_copy_f, t_merge_f, c_flat = min((run(False) for _ in range(2)),
                                      key=lambda p: p[0] + p[1])
    t_eng, t_flat = t_copy_e + t_merge_e, t_copy_f + t_merge_f
    rows["copier_maps"] = w
    rows["copier_engine_copy_s"] = round(t_copy_e, 4)
    rows["copier_engine_merge_s"] = round(t_merge_e, 4)
    rows["copier_flat_copy_s"] = round(t_copy_f, 4)
    rows["copier_flat_merge_s"] = round(t_merge_f, 4)
    rows["copier_engine_s"] = round(t_eng, 4)
    rows["copier_flat_s"] = round(t_flat, 4)
    rows["copier_engine_speedup"] = round(t_flat / t_eng, 3)
    rows["copier_merge_phase_speedup"] = round(t_merge_f / t_merge_e, 3)
    rows["copier_engine_inmem_merges"] = c_eng.inmem_merges
    rows["copier_engine_disk_merges"] = c_eng.disk_merges
    rows["copier_engine_segments_disk"] = c_eng.spilled_to_disk
    rows["copier_flat_segments_disk"] = c_flat.spilled_to_disk
    log(f"[copier] {w} maps, budget ~6 segments: engine copy "
        f"{t_copy_e:.3f}s + merge {t_merge_e:.3f}s "
        f"({c_eng.inmem_merges} in-mem + {c_eng.disk_merges} disk-run "
        f"merges, {c_eng.spilled_to_disk} disk segments) vs flat copy "
        f"{t_copy_f:.3f}s + merge {t_merge_f:.3f}s "
        f"({c_flat.spilled_to_disk} disk segments) -> end-to-end "
        f"{t_flat / t_eng:.2f}x, merge_reduce phase "
        f"{t_merge_f / t_merge_e:.2f}x")
    return t_eng, t_flat


def _write_spill_file(dirname: str, name: str, records) -> "tuple[str, dict]":
    import io as _io

    from tpumr.io import ifile

    buf = _io.BytesIO()
    w = ifile.Writer(buf, codec="none")
    w.start_partition()
    for kb, vb in records:
        w.append_raw(kb, vb)
    w.end_partition()
    index = w.close()
    path = os.path.join(dirname, name)
    with open(path, "wb") as f:
        f.write(buf.getvalue())
    return path, index


class _WireStub:
    """The tracker's shuffle-serving surface behind a real RpcServer:
    serve_chunk/serve_batch over real spill files through the fd cache,
    plus an optional per-RPC hold emulating request overhead — the
    fixed cost batching exists to amortize."""

    MAX_CHUNK = 4 << 20

    def __init__(self, outputs: dict, delay_s: float = 0.0) -> None:
        from tpumr.mapred.tasktracker import SpillFdCache
        self.outputs = outputs
        self.delay_s = delay_s
        self.fds = SpillFdCache(64)
        self.rpcs = 0

    def get_protocol_version(self) -> int:
        return 7

    def get_map_output_chunk(self, job_id, map_index, partition, offset,
                             max_bytes, wire="none") -> dict:
        from tpumr.mapred.tasktracker import serve_chunk
        self.rpcs += 1
        if self.delay_s:
            time.sleep(self.delay_s)
        path, index = self.outputs[map_index]
        return serve_chunk(self.fds, path, index, partition, offset,
                           max_bytes, self.MAX_CHUNK, wire)

    def get_map_outputs_batch(self, job_id, partition, map_indexes,
                              max_bytes_each=1 << 20,
                              max_total_bytes=8 << 20,
                              wire="none") -> list:
        from tpumr.mapred.tasktracker import serve_batch
        self.rpcs += 1
        if self.delay_s:
            time.sleep(self.delay_s)
        return serve_batch(self.fds, lambda m: self.outputs[m], partition,
                           list(map_indexes), max_bytes_each,
                           max_total_bytes, self.MAX_CHUNK, wire)


def bench_wire(rows: dict) -> None:
    """The wire rows: the rebuilt copy path over a real reactor
    RpcServer on loopback — pipelined chunk streams, wide-job batched
    fetches, and tlz wire compression."""
    from tpumr.io.compress import wire_codec_or_none
    from tpumr.ipc.rpc import RpcServer
    from tpumr.mapred.api import RawComparator
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.shuffle_copier import (RemoteChunkSource,
                                             ShuffleCopier)
    from tpumr.mapred.tasktracker import make_map_locator

    job = "job_bench_0001"

    def start(stub: _WireStub) -> RpcServer:
        s = RpcServer(stub, reactor=True,
                      fast_methods={"get_protocol_version"})
        s.uncached_methods = {"get_map_output_chunk",
                              "get_map_outputs_batch"}
        return s.start()

    def locator(port: int, maps, conns: int = 2):
        events = [{"map_index": m, "attempt_id": "a%d" % m,
                   "shuffle_addr": "127.0.0.1:%d" % port,
                   "status": "SUCCEEDED"} for m in maps]
        return make_map_locator(lambda cursor: events[cursor:], None,
                                poll_s=0.01, timeout_s=30.0,
                                conns_per_target=conns)

    def conf_for(**kv) -> "JobConf":
        conf = JobConf()
        conf.set_output_key_comparator_class(RawComparator)
        conf.set("tpumr.shuffle.chunk.bytes", 64 * 1024)
        conf.set("tpumr.shuffle.ram.mb", 64)
        for k, v in kv.items():
            conf.set(k, v)
        return conf

    tmp = tempfile.mkdtemp(prefix="bench-shuffle-wire-")
    try:
        # ---- pipelined chunk stream vs one-at-a-time, one big output.
        # No artificial hold: the reactor serves one connection's
        # pipeline from one pool slot, so the honest win is overlapping
        # client-side decode/landing with server-side pread+send.
        n_big = 12_000 if SMALL else 60_000
        big = [(b"k%08d" % i, b"x" * 120) for i in range(n_big)]
        stub = _WireStub({0: _write_spill_file(tmp, "big", big)})
        srv = start(stub)
        try:
            def pull(depth: int) -> "tuple[float, int]":
                conf = conf_for(**{
                    "tpumr.shuffle.fetch.pipeline.depth": depth,
                    "tpumr.shuffle.wire.codec": "none"})
                src = RemoteChunkSource(conf, job, locator(srv.port, [0]))

                def go() -> int:
                    return sum(len(c["data"])
                               for c in src.fetch_chunks(0, 0))

                return min((timed(go) for _ in range(3)),
                           key=lambda p: p[0])

            t_seq, nbytes = pull(1)
            t_pipe, _ = pull(4)
        finally:
            srv.stop()
        rows["wire_stream_bytes"] = nbytes
        rows["wire_seq_mb_s"] = round(nbytes / t_seq / 1e6, 1)
        rows["wire_pipeline_mb_s"] = round(nbytes / t_pipe / 1e6, 1)
        rows["wire_pipeline_speedup"] = round(t_seq / t_pipe, 3)
        log(f"[wire-pipeline] {nbytes / 1e6:.1f} MB in 64 KiB chunks: "
            f"depth 1 {nbytes / t_seq / 1e6:.0f} MB/s, depth 4 "
            f"{nbytes / t_pipe / 1e6:.0f} MB/s -> "
            f"{t_seq / t_pipe:.2f}x")

        # ---- wide job: many tiny segments, batched vs per-segment.
        # A 3 ms per-RPC hold stands in for real request overhead
        # (scheduling, auth, framing) — the regime where one
        # get_map_outputs_batch frame replaces batch.segments RPCs.
        w_wide = 24 if SMALL else 96
        tiny = {m: _write_spill_file(tmp, "t%d" % m,
                                     [(b"k%04d" % i, b"v" * 10)
                                      for i in range(40)])
                for m in range(w_wide)}
        stub2 = _WireStub(tiny, delay_s=0.003)
        srv2 = start(stub2)
        try:
            def copy_all(batch_segments: int) -> "tuple[float, int]":
                conf = conf_for(**{
                    "tpumr.shuffle.batch.segments": batch_segments,
                    "tpumr.shuffle.wire.codec": "none",
                    "tpumr.shuffle.parallel.copies": 4})
                src = RemoteChunkSource(
                    conf, job, locator(srv2.port, range(w_wide)))
                spill_dir = tempfile.mkdtemp(dir=tmp)
                rpc0 = stub2.rpcs
                t0 = time.perf_counter()
                segs = ShuffleCopier(conf, src, w_wide, 0, spill_dir,
                                     on_fetch_failure=lambda m, a: None
                                     ).copy_all()
                t = time.perf_counter() - t0
                n = sum(drain(s) for s in segs)
                for s in segs:
                    s.close()
                assert n == w_wide * 40, f"wide copy lost records: {n}"
                return t, stub2.rpcs - rpc0

            t_per, rpc_per = min((copy_all(1) for _ in range(2)),
                                 key=lambda p: p[0])
            t_bat, rpc_bat = min((copy_all(16) for _ in range(2)),
                                 key=lambda p: p[0])
        finally:
            srv2.stop()
        rows["wire_wide_maps"] = w_wide
        rows["wire_perseg_s"] = round(t_per, 4)
        rows["wire_perseg_rpcs"] = rpc_per
        rows["wire_batch_s"] = round(t_bat, 4)
        rows["wire_batch_rpcs"] = rpc_bat
        rows["wire_batch_speedup"] = round(t_per / t_bat, 3)
        log(f"[wire-batch] {w_wide} tiny segments at 3ms/RPC: "
            f"per-segment {t_per:.3f}s ({rpc_per} RPCs) vs batched "
            f"{t_bat:.3f}s ({rpc_bat} RPCs) -> {t_per / t_bat:.2f}x")

        # ---- wire compression: compressible payload, tlz vs raw.
        # Throughput is RAW payload bytes per wall second both ways, so
        # the rows compare like-for-like.
        n_comp = 8_000 if SMALL else 40_000
        comp = [(b"k%08d" % i, b"the quick brown fox " * 6)
                for i in range(n_comp)]
        stub3 = _WireStub({0: _write_spill_file(tmp, "comp", comp)})
        srv3 = start(stub3)
        try:
            def pull_wire(wirec: str) -> "tuple[float, int, int]":
                conf = conf_for(**{"tpumr.shuffle.wire.codec": wirec})
                src = RemoteChunkSource(conf, job,
                                        locator(srv3.port, [0]))

                def go() -> "tuple[int, int]":
                    raw = wire = 0
                    for c in src.fetch_chunks(0, 0):
                        raw += len(c["data"])
                        wire += c.get("wire_len", len(c["data"]))
                    return raw, wire

                t, (raw, wire) = min((timed(go) for _ in range(3)),
                                     key=lambda p: p[0])
                return t, raw, wire

            codec = wire_codec_or_none("tlz")
            t_raw, raw_b, _ = pull_wire("none")
            rows["wire_codec"] = codec
            rows["wire_raw_mb_s"] = round(raw_b / t_raw / 1e6, 1)
            if codec != "none":
                t_cmp, _, wire_b = pull_wire(codec)
                rows["wire_compress_ratio"] = round(wire_b / raw_b, 3)
                rows["wire_compressed_mb_s"] = round(
                    raw_b / t_cmp / 1e6, 1)
                log(f"[wire-codec] {raw_b / 1e6:.1f} MB payload: raw "
                    f"{raw_b / t_raw / 1e6:.0f} MB/s, {codec} "
                    f"{raw_b / t_cmp / 1e6:.0f} MB/s at "
                    f"{wire_b / raw_b:.2f}x wire bytes")
            else:
                log(f"[wire-codec] no native codec in this build: raw "
                    f"{raw_b / t_raw / 1e6:.0f} MB/s")
        finally:
            srv3.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> None:
    prior: dict = {}
    try:
        with open("bench_shuffle.json") as f:
            prior = json.load(f)
    except (OSError, ValueError):
        pass
    rows: dict = {}
    r_eng, r_flat = bench_merge_throughput(rows)
    bench_bounded_fanin(rows)
    bench_copier(rows)
    bench_wire(rows)
    for k in ("merge_engine_speedup", "copier_engine_speedup",
              "wire_pipeline_speedup", "wire_batch_speedup",
              "wire_compress_ratio"):
        if k in rows:
            log(f"[vs prior] {k}: {prior.get(k, '(new)')} -> {rows[k]}")
    with open("bench_shuffle.json", "w") as f:
        json.dump(rows, f, sort_keys=True, indent=1)
    log(f"detail rows -> bench_shuffle.json: "
        f"{json.dumps(rows, sort_keys=True)}")
    print(json.dumps({
        "metric": f"wide-shuffle merge throughput, {W} segments x {R} "
                  f"records: merge engine (in-memory Timsort-galloping "
                  f"merge, the background merger's kernel) vs the flat "
                  f"key-fn heap merge over all segments",
        "value": round(r_eng),
        "unit": "records/sec",
        "vs_baseline": round(r_eng / r_flat, 2),
    }))


if __name__ == "__main__":
    main()
