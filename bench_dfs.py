"""DFS saturation bench: where does the NameNode (and read path) melt?

Ramps a simulated DFS-client fleet (``tpumr/scale/simdfs.py`` — real
``DFSClient`` instances, real RPC, real DataNode block reads; the only
synthetic thing is the op generator) against a FRESH in-process
MiniDFSCluster per rung, and records both sides of every rung:

- ``nn_op_p50_s`` / ``nn_op_p99_s`` — the NameNode's own per-op
  handling latency (``nn_op_seconds{op=}`` merged across families,
  with the per-op p99 map alongside);
- ``lock_wait_p99_s`` / ``lock_hold_p99_s`` and the derived
  ``lock_wait_share`` (lock wait p99 / op p99 — ~1.0 means the
  namespace lock IS the latency, the signature the fine-grained-
  locking roadmap item would have to move);
- ``editlog_sync_p99_s``  — the fsync floor under every mutation;
- ``read_mb_s`` / ``read_rtt_p99_s`` / ``dn_read_p99_s`` — data-plane
  throughput and tails, client- and datanode-side;
- ``hot_top1_share``      — the skew the SpaceSaving hot-block
  pipeline (DN sketch → heartbeat piggyback → NN ``/hotblocks``)
  surfaces: the designated hot file must dominate;
- ``hot_top1_replicas`` / ``hot_top1_boost`` — the auto-replication
  receipt: the seed files start at replication=2, so a boosted hot
  block visibly spreads to a third datanode under sustained skew;
- ``editlog_group_ops_mean`` — mutations absorbed per editlog fsync
  (group commit coalescing; 1.0 means every mutation paid its own);
- ``lag_p99_s``           — client schedule overrun: the first
  externally visible saturation symptom.

The report names the max sustainable client fleet at a DUAL SLO —
NameNode op p99 (``tpumr.dfs.bench.op.slo.ms``) AND client read
round-trip p99 (``tpumr.dfs.bench.read.slo.ms``) — the baseline every
DFS-side change must move (or at least not regress).

Output contract (same as ``bench_scale.py``): ONE JSON line on stdout
{"metric", "value", "unit", "vs_baseline"}; per-rung rows go to stderr
and ``bench_dfs.json``. env BENCH_SCALE=small (or --smoke) shrinks the
ramp for CI; --assert-slo exits 3 when the smoke fleet can't hold the
dual SLO. env TPUMR_DFS_PROM_OUT=PATH scrapes the last rung's live
NameNode ``/metrics/prom`` into PATH (the CI artifact proving the
exposition renders under load).
"""

from __future__ import annotations

import json
import os
import sys

# measure the production configuration: the debug lock-order assertion
# (metrics/locks.py) is a development aid a deployed namenode would run
# without (python -O); honor an explicit override. Must be set before
# any tpumr import (the flag is read at module load).
os.environ.setdefault("TPUMR_LOCK_ORDER_CHECK", "0")


def log(*a: object) -> None:
    print(*a, file=sys.stderr, flush=True)


SMALL = os.environ.get("BENCH_SCALE") == "small" or "--smoke" in sys.argv

#: client-fleet ramp (≥ 4 rungs in every mode — the rows ARE the
#: trajectory) and the per-client op cadence they schedule against
FLEETS = [2, 4, 6, 8] if SMALL else [8, 16, 32, 64, 128]
INTERVAL_S = 0.05
MEASURE_S = 3.0 if SMALL else 8.0
DATANODES = 2 if SMALL else 3
N_FILES = 4 if SMALL else 8
FILE_BYTES = 1 << 16 if SMALL else 1 << 18


def _slos() -> "tuple[float, float]":
    from tpumr.core import confkeys
    from tpumr.mapred.jobconf import JobConf
    conf = JobConf()
    return (confkeys.get_int(conf, "tpumr.dfs.bench.op.slo.ms") / 1e3,
            confkeys.get_int(conf, "tpumr.dfs.bench.read.slo.ms") / 1e3)


def _log_row(row: dict) -> None:
    log(f"[dfs] {row['clients']:4d} clients: nn op p50 "
        f"{row['nn_op_p50_s'] * 1e3:.2f}ms p99 "
        f"{row['nn_op_p99_s'] * 1e3:.2f}ms · lock wait p99 "
        f"{row['lock_wait_p99_s'] * 1e3:.2f}ms hold "
        f"{row['lock_hold_p99_s'] * 1e3:.2f}ms (share "
        f"{row['lock_wait_share']:.2f}) · editlog sync p99 "
        f"{row['editlog_sync_p99_s'] * 1e3:.2f}ms · read "
        f"{row['read_mb_s']:.1f}MB/s rtt p99 "
        f"{row['read_rtt_p99_s'] * 1e3:.2f}ms · lag p99 "
        f"{row['lag_p99_s'] * 1e3:.2f}ms · hot top1 "
        f"{row['hot_top1_share']:.0%} "
        f"({row.get('hot_top1_replicas', 0)} repl, boost "
        f"{row.get('hot_top1_boost', 0)}) · grp "
        f"{row.get('editlog_group_ops_mean', 0):.1f} · "
        f"{row['ops']} ops"
        + ("" if row["completed"]
           else f" · {row['errors']} ERRORS"))


def run_bench(fleets: "list[int] | None" = None) -> dict:
    from tpumr.scale.simdfs import run_dfs_step
    op_slo_s, read_slo_s = _slos()
    prom_out = os.environ.get("TPUMR_DFS_PROM_OUT")
    fleets = fleets or FLEETS
    rows = []
    for i, n in enumerate(fleets):
        row = run_dfs_step(
            n, interval_s=INTERVAL_S, measure_s=MEASURE_S,
            num_datanodes=DATANODES, n_files=N_FILES,
            file_bytes=FILE_BYTES, seed=n,
            # scrape the LAST (biggest) rung: the exposition artifact
            # should show the NameNode at max load
            prom_out=prom_out if i == len(fleets) - 1 else None)
        rows.append(row)
        _log_row(row)
    # the DUAL SLO: the NameNode must handle ops inside op_slo AND the
    # end-to-end read path (NN locate + DN fetch) must stay inside
    # read_slo — a rung passing one while blowing the other is NOT
    # sustainable (fast metadata is no comfort to a stalled reader)
    sustainable = [r["clients"] for r in rows
                   if r["completed"]
                   and r["nn_op_p99_s"] <= op_slo_s
                   and r["read_rtt_p99_s"] <= read_slo_s]
    return {
        "interval_s": INTERVAL_S,
        "measure_s": MEASURE_S,
        "datanodes": DATANODES,
        "files": N_FILES,
        "file_bytes": FILE_BYTES,
        "op_slo_s": op_slo_s,
        "read_slo_s": read_slo_s,
        "slo_series": ["nn_op_p99_s", "read_rtt_p99_s"],
        "max_sustainable_clients": max(sustainable, default=0),
        # highest replica count the hot block reached across the ramp:
        # seeds write at replication=2, so any value above 2 is the
        # hot-block auto-replication policy demonstrably spreading load
        "hot_max_replicas": max(
            (r.get("hot_top1_replicas", 0) for r in rows), default=0),
        "rows": rows,
    }


# ------------------------------------------------------------ recovery


def run_recovery_bench() -> "list[dict]":
    """The committed recovery rows (bench_scale's recovery_rows
    pattern): every row carries recovery_s vs its registered SLO and
    an ok verdict; a step that dies contributes an error row instead
    of killing the bench."""
    from tpumr.scale.simdfs import (run_dn_kill_recovery,
                                    run_nn_kill_recovery)
    rows: "list[dict]" = []
    try:
        rows.extend(run_nn_kill_recovery(
            num_datanodes=DATANODES, n_files=N_FILES,
            file_bytes=FILE_BYTES))
    except Exception as e:  # noqa: BLE001
        log(f"[dfs] recovery nn-kill step FAILED: {e!r}")
        rows.append({"kind": "nn_kill", "error": repr(e)})
    try:
        rows.append(run_dn_kill_recovery(
            num_datanodes=DATANODES + 1, n_files=N_FILES,
            file_bytes=FILE_BYTES))
    except Exception as e:  # noqa: BLE001
        log(f"[dfs] recovery dn-kill step FAILED: {e!r}")
        rows.append({"kind": "dn_kill_replication_restored",
                     "error": repr(e)})
    for r in rows:
        if "error" in r:
            log(f"[dfs] recovery {r['kind']}: ERROR {r['error']}")
        else:
            log(f"[dfs] recovery {r['kind']}: {r['recovery_s']:.2f}s "
                f"(slo {r['slo_s']:.0f}s) "
                f"{'ok' if r['ok'] else 'BREACH'}")
    return rows


def compare_with_prior(prior: "dict | None", report: dict) -> None:
    """One stderr line per common fleet size against a prior
    bench_dfs.json — the before/after of a DFS change in one glance."""
    if not prior or not prior.get("rows"):
        return
    old = {r["clients"]: r for r in prior["rows"]}
    for row in report["rows"]:
        o = old.get(row["clients"])
        if o is None:
            continue
        log(f"[dfs] vs prior @ {row['clients']:4d} clients: nn op p99 "
            f"{o.get('nn_op_p99_s', 0) * 1e3:.2f}"
            f"->{row['nn_op_p99_s'] * 1e3:.2f}ms · read rtt p99 "
            f"{o.get('read_rtt_p99_s', 0) * 1e3:.2f}"
            f"->{row['read_rtt_p99_s'] * 1e3:.2f}ms · "
            f"lock_wait_share {o.get('lock_wait_share', 0):.2f}"
            f"->{row['lock_wait_share']:.2f}")
    log(f"[dfs] vs prior: max sustainable "
        f"{prior.get('max_sustainable_clients', 0)}"
        f"->{report['max_sustainable_clients']} clients · hot max "
        f"replicas {prior.get('hot_max_replicas', 0)}"
        f"->{report['hot_max_replicas']}")


def main() -> None:
    prior = None
    try:
        with open("bench_dfs.json") as f:
            prior = json.load(f)
    except (OSError, ValueError):
        pass
    if "--recovery-only" in sys.argv:
        # refresh ONLY the recovery rows, preserving every other
        # committed key (the bench_scale --recovery-only contract)
        report = prior or {"rows": []}
        report["recovery_rows"] = run_recovery_bench()
        with open("bench_dfs.json", "w") as f:
            json.dump(report, f, sort_keys=True, indent=1)
        judged = [r for r in report["recovery_rows"]
                  if "error" not in r]
        print(json.dumps({
            "metric": "dfs recovery: rows inside their SLO "
                      "(nn-kill safemode exit / first client success, "
                      "dn-kill replication restored)",
            "value": sum(1 for r in judged if r["ok"]),
            "unit": f"of {len(report['recovery_rows'])} rows",
            "vs_baseline": 1.0,
        }))
        if "--assert-slo" in sys.argv and (
                len(judged) != len(report["recovery_rows"])
                or not all(r["ok"] for r in judged)):
            log("[dfs] RECOVERY SLO FAILED")
            sys.exit(3)
        return
    report = run_bench()
    if prior and prior.get("recovery_rows") is not None:
        # committed recovery rows survive a saturation-only rerun
        report["recovery_rows"] = prior["recovery_rows"]
    with open("bench_dfs.json", "w") as f:
        json.dump(report, f, sort_keys=True, indent=1)
    log(f"detail rows -> bench_dfs.json: "
        f"{json.dumps(report, sort_keys=True)}")
    compare_with_prior(prior, report)
    rows = report["rows"]
    print(json.dumps({
        "metric": f"dfs: max simulated-client fleet (of ramp "
                  f"{[r['clients'] for r in rows]}, "
                  f"{report['interval_s'] * 1000:.0f}ms op cadence, "
                  f"{report['datanodes']} datanodes) the namenode "
                  f"sustains with nn op p99 <= "
                  f"{report['op_slo_s'] * 1000:.0f}ms AND read rtt "
                  f"p99 <= {report['read_slo_s'] * 1000:.0f}ms",
        "value": report["max_sustainable_clients"],
        "unit": "clients",
        # this bench IS the DFS baseline; nothing earlier exists
        "vs_baseline": 1.0,
    }))
    if "--assert-slo" in sys.argv:
        if report["max_sustainable_clients"] < max(FLEETS):
            # CI regression gate (smoke sizes only — the full ramp is
            # a measurement, not a gate): the whole smoke fleet must
            # hold the dual SLO, or the DFS serving path regressed
            log(f"[dfs] SLO FAILED: sustained "
                f"{report['max_sustainable_clients']} of {max(FLEETS)} "
                f"clients at the dual SLO (op "
                f"{report['op_slo_s'] * 1000:.0f}ms / read "
                f"{report['read_slo_s'] * 1000:.0f}ms p99)")
            sys.exit(3)
        # the skew pipeline is part of the contract: every gated row
        # must show the hot file dominating the hot-block table (the
        # DN sketch → heartbeat → NN fold path went through)
        for row in rows:
            if row["hot_top1_share"] < 0.25:
                log(f"[dfs] HOT-BLOCK PIPELINE FAILED @ "
                    f"{row['clients']} clients: top1 share "
                    f"{row['hot_top1_share']:.2f} < 0.25")
                sys.exit(3)


if __name__ == "__main__":
    main()
