# Bash completion for the tpumr CLI.
# ≈ src/contrib/bash-tab-completion/hadoop.sh (completes commands, then
# per-command flags, then filesystem paths).
#
# Install:  source misc/tpumr-completion.bash
#           (or drop it into /etc/bash_completion.d/)

_tpumr_complete() {
    local cur prev cmds
    COMPREPLY=()
    cur="${COMP_WORDS[COMP_CWORD]}"
    prev="${COMP_WORDS[COMP_CWORD-1]}"
    cmds="namenode datanode secondarynamenode jobtracker tasktracker \
historyserver fs job balancer fsck dfsadmin pipes streaming examples \
distcp archive rumen failmon gridmix keys queue mradmin daemonlog \
fetchdt version"

    if [[ ${COMP_CWORD} -eq 1 ]]; then
        COMPREPLY=( $(compgen -W "${cmds}" -- "${cur}") )
        return 0
    fi

    case "${COMP_WORDS[1]}" in
        fs)
            if [[ ${COMP_CWORD} -eq 2 ]]; then
                COMPREPLY=( $(compgen -W "-ls -lsr -cat -put -get -cp -mv \
-rm -rmr -mkdir -touchz -du -dus -count -chmod -chown -tail -text -stat \
-test -expunge -help" -- "${cur}") )
                return 0
            fi
            ;;
        job)
            if [[ ${COMP_CWORD} -eq 2 ]]; then
                COMPREPLY=( $(compgen -W "-list -status -kill -counters \
-events -history -diagnose" -- "${cur}") )
                return 0
            fi
            ;;
        dfsadmin)
            if [[ ${COMP_CWORD} -eq 2 ]]; then
                COMPREPLY=( $(compgen -W "-report -safemode -setQuota \
-clrQuota -setSpaceQuota -clrSpaceQuota -decommission -recommission \
-refreshNodes" -- "${cur}") )
                return 0
            fi
            ;;
        failmon)
            if [[ ${COMP_CWORD} -eq 2 ]]; then
                COMPREPLY=( $(compgen -W "-collect -merge" -- "${cur}") )
                return 0
            fi
            ;;
        examples)
            if [[ ${COMP_CWORD} -eq 2 ]]; then
                COMPREPLY=( $(compgen -W "wordcount grep pi kmeans matmul \
sort terasort teragen teravalidate join secondarysort sleep randomwriter" \
                    -- "${cur}") )
                return 0
            fi
            ;;
        streaming|pipes)
            COMPREPLY=( $(compgen -W "-input -output -mapper -reducer \
-combiner -io -D -jt -files" -- "${cur}") )
            return 0
            ;;
    esac
    # default: local paths (input/output files, scripts, binaries)
    COMPREPLY=( $(compgen -f -- "${cur}") )
    return 0
}

complete -F _tpumr_complete tpumr
