"""TeraSort at scale through the distributed shuffle copier.

BASELINE workload 5 (10 GB, 100M x 100B) end-to-end on a real
mini-cluster: map spills (tlz-compressed), tasktracker chunked serving,
the parallel RAM-budgeted reduce copier (segments in RAM or spilled,
counted), streamed merge, and a full teravalidate. Round 2's 772 s scale
proof predates the copier (it ran the serial LocalJobRunner shuffle);
this is the path `ReduceTask.java:659,1080` describes.

Host-only (no TPU needed). Run:  python misc/bench_terasort_scale.py
[records] [reduces]; prints one JSON line, results belong in BASELINE.md.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    records = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000_000
    reduces = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    #: reuse an existing teragen dir (skip the 3-min gen) and/or raise
    #: the copier RAM budget: TERASORT_GEN_DIR=..., TERASORT_RAM_MB=...
    #: TERASORT_DEVICE=1 runs the dense/gang-reduce shuffle instead of
    #: the per-record host path (vectorized end-to-end; sorts on
    #: whatever backend JAX has — pin TPUMR_JAX_PLATFORM=cpu for the
    #: host-dense row)
    gen_dir = os.environ.get("TERASORT_GEN_DIR")
    ram_mb = float(os.environ.get("TERASORT_RAM_MB", 0) or 0)
    device = os.environ.get("TERASORT_DEVICE") == "1"
    plat = os.environ.get("TPUMR_JAX_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)

    from tpumr.cli import main as cli_main
    from tpumr.core.counters import TaskCounter
    from tpumr.examples.terasort import make_terasort_conf
    from tpumr.mapred.job_client import JobClient
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.mini_cluster import MiniMRCluster

    work = tempfile.mkdtemp(prefix="tpumr-terasort-scale-")
    rows: dict = {"records": records, "gb": records * 100 / 1e9,
                  "reduces": reduces}

    if gen_dir:
        gen_uri = gen_dir if "://" in gen_dir else f"file://{gen_dir}"
        rows["teragen_s"] = 0.0
    else:
        gen_uri = f"file://{work}/gen"
        t0 = time.time()
        assert cli_main(["examples", "teragen", str(records),
                         gen_uri, "-m", "8"]) == 0
        rows["teragen_s"] = round(time.time() - t0, 1)
        print(f"[teragen] {records:,} records: {rows['teragen_s']}s",
              file=sys.stderr, flush=True)

    base = JobConf()
    with MiniMRCluster(num_trackers=2, cpu_slots=2, tpu_slots=0,
                       conf=base) as c:
        conf = c.create_job_conf()
        ts = make_terasort_conf(gen_uri, f"file://{work}/out", reduces,
                                device_shuffle=device)
        rows["device_shuffle"] = device
        for k, v in ts:
            conf.set(k, v)
        # production shuffle config: tlz-compressed map outputs through
        # the parallel RAM-budgeted copier
        conf.set("mapred.compress.map.output", True)
        conf.set("mapred.map.output.compression.codec", "tlz")
        if ram_mb:
            conf.set("tpumr.shuffle.ram.mb", ram_mb)
            rows["shuffle_ram_mb"] = ram_mb
        t0 = time.time()
        result = JobClient(conf).run_job(conf)
        rows["terasort_s"] = round(time.time() - t0, 1)
        assert result.successful, result.error
        cv = result.counters.value
        if device:
            # which backend ACTUALLY sorted (the gang reduce stamps a
            # counter when jax resolved to a real accelerator) — the
            # artifact must say "backend: tpu" only when it was
            from tpumr.core.counters import BackendCounter
            rows["backend"] = ("tpu" if cv(
                BackendCounter.GROUP,
                BackendCounter.DEVICE_SORT_ON_ACCEL) else "cpu")
        rows["shuffle_bytes"] = cv(TaskCounter.FRAMEWORK_GROUP,
                                   TaskCounter.REDUCE_SHUFFLE_BYTES)
        rows["segments_mem"] = cv(
            TaskCounter.FRAMEWORK_GROUP,
            TaskCounter.REDUCE_SHUFFLE_SEGMENTS_MEM)
        rows["segments_disk"] = cv(
            TaskCounter.FRAMEWORK_GROUP,
            TaskCounter.REDUCE_SHUFFLE_SEGMENTS_DISK)

    t0 = time.time()
    import contextlib
    with contextlib.redirect_stdout(sys.stderr):   # keep stdout pure JSON
        assert cli_main(["examples", "teravalidate", f"file://{work}/out",
                         f"file://{work}/validate"]) == 0
    rows["teravalidate_s"] = round(time.time() - t0, 1)
    rows["mb_per_s"] = round(records * 100 / 1e6 / rows["terasort_s"], 1)
    print(json.dumps(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
