/* task-controller — privilege-separated task launcher.
 *
 * ≈ the reference's setuid task-controller (src/c++/task-controller/,
 * 2.8k C: the LinuxTaskController backend that launches task processes
 * as the submitting user, with path validation so a compromised tracker
 * cannot aim it outside the task sandbox).  Security checks mirror
 * impl/task-controller.c:529-540 (reference): refuse root and system
 * uids, refuse banned users, and validate the task dir against the
 * tracker-local dirs named in a root-owned config file.
 *
 * Usage: task-controller <user> <task-dir> <stdout-file> <cmd> [args...]
 *
 * Config (only consulted when running setuid-root):
 *   /etc/tpumr/task-controller.cfg, overridable at build time via
 *   -DTC_CONF_PATH=...  Must be owned by root and not group/world
 *   writable.  Keys (one `key=value` per line, '#' comments):
 *     min.user.id=1000          lowest uid allowed to run tasks
 *     banned.users=root,daemon  comma list of refused user names
 *     allowed.local.dirs=/a,/b  comma list of absolute prefixes the
 *                               task dir must live under
 *
 * - validates the task dir exists, is owned by the target user, and
 *   contains no ".." traversal;
 * - when running as root (installed setuid, production): refuses
 *   uid 0 and uids below min.user.id, requires the task dir to be
 *   inside an allowed local dir, then setgid/setuid to the target
 *   user before exec;
 * - when not root (tests, single-user clusters): requires <user> to be
 *   the current user and just sandboxes cwd/env;
 * - clears the environment except PATH/HOME/LANG + TPUMR_* passthrough,
 *   chdirs into the task dir, redirects stdout/stderr to the log file,
 *   then execs the command.
 */

#include <errno.h>
#include <fcntl.h>
#include <grp.h>
#include <limits.h>
#include <pwd.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#ifndef TC_CONF_PATH
#define TC_CONF_PATH "/etc/tpumr/task-controller.cfg"
#endif

#define TC_DEFAULT_MIN_UID 1000

extern char** environ;

static int fail(const char* msg) {
  fprintf(stderr, "task-controller: %s (errno=%s)\n", msg,
          errno ? strerror(errno) : "0");
  return 10;
}

static int validate_path(const char* p) {
  if (p[0] != '/') return -1;               /* absolute only */
  if (strstr(p, "/../") || strstr(p, "/./")) return -1;
  size_t n = strlen(p);
  if (n >= 3 && strcmp(p + n - 3, "/..") == 0) return -1;
  if (n >= 2 && strcmp(p + n - 2, "/.") == 0) return -1;
  return 0;
}

/* Root-mode policy loaded from the root-owned config file. */
struct tc_config {
  long min_uid;
  char banned[1024];        /* comma list, surrounded by commas */
  char allowed_dirs[4096];  /* comma list of absolute prefixes */
};

static int load_config(struct tc_config* cfg) {
  struct stat st;
  FILE* f;
  int fd;
  char line[4096];

  cfg->min_uid = TC_DEFAULT_MIN_UID;
  snprintf(cfg->banned, sizeof(cfg->banned), ",root,daemon,bin,");
  cfg->allowed_dirs[0] = '\0';

  /* open first, then fstat the fd — a stat-then-fopen pair is a TOCTOU
   * window in a setuid binary (reference checks the fd it reads) */
  fd = open(TC_CONF_PATH, O_RDONLY | O_NOFOLLOW);
  if (fd < 0)
    return fail("config file " TC_CONF_PATH " required when running as root");
  if (fstat(fd, &st) != 0) { close(fd); return fail("cannot stat config"); }
  if (!S_ISREG(st.st_mode)) { close(fd); return fail("config not a regular file"); }
  if (st.st_uid != 0) { close(fd); return fail("config file must be owned by root"); }
  if (st.st_mode & (S_IWGRP | S_IWOTH)) {
    close(fd);
    return fail("config file must not be group/world writable");
  }

  f = fdopen(fd, "r");
  if (!f) { close(fd); return fail("cannot open config file"); }
  /* any malformed or over-long policy value is a hard error, never a
   * silently-weaker policy (fail closed: this binary runs setuid root) */
  while (fgets(line, sizeof(line), f)) {
    char* nl = strchr(line, '\n');
    char* end = NULL;
    char* eq;
    int n;
    if (!nl && !feof(f)) {
      fclose(f);
      return fail("config line too long");
    }
    if (nl) *nl = '\0';
    if (line[0] == '#' || line[0] == '\0') continue;
    eq = strchr(line, '=');
    if (!eq) continue;
    *eq = '\0';
    if (strcmp(line, "min.user.id") == 0) {
      errno = 0;
      cfg->min_uid = strtol(eq + 1, &end, 10);
      if (errno || end == eq + 1 || *end != '\0' || cfg->min_uid < 1) {
        fclose(f);
        return fail("invalid min.user.id (must be a positive integer)");
      }
    } else if (strcmp(line, "banned.users") == 0) {
      n = snprintf(cfg->banned, sizeof(cfg->banned), ",%s,", eq + 1);
      if (n < 0 || (size_t)n >= sizeof(cfg->banned)) {
        fclose(f);
        return fail("banned.users value too long");
      }
    } else if (strcmp(line, "allowed.local.dirs") == 0) {
      n = snprintf(cfg->allowed_dirs, sizeof(cfg->allowed_dirs), "%s",
                   eq + 1);
      if (n < 0 || (size_t)n >= sizeof(cfg->allowed_dirs)) {
        fclose(f);
        return fail("allowed.local.dirs value too long");
      }
    }
  }
  fclose(f);
  if (cfg->allowed_dirs[0] == '\0')
    return fail("config must set allowed.local.dirs");
  return 0;
}

static int user_banned(const struct tc_config* cfg, const char* user) {
  char needle[256];
  if (strlen(user) > sizeof(needle) - 3) return 1;
  snprintf(needle, sizeof(needle), ",%s,", user);
  return strstr(cfg->banned, needle) != NULL;
}

/* Resolve the parent directory of `path` through symlinks and re-attach
 * the final component (which may not exist yet, e.g. the logfile).  The
 * final component itself is kept symlink-safe by O_NOFOLLOW at open. */
static int resolve_parent(const char* path, char* out, size_t outlen) {
  char parent[PATH_MAX];
  char parent_real[PATH_MAX];
  const char* slash = strrchr(path, '/');
  size_t plen;
  if (!slash || slash == path) return -1;     /* "/x" or no slash: refuse */
  plen = (size_t)(slash - path);
  if (plen >= sizeof(parent)) return -1;
  memcpy(parent, path, plen);
  parent[plen] = '\0';
  if (!realpath(parent, parent_real)) return -1;
  if (strlen(parent_real) + 1 + strlen(slash + 1) + 1 > outlen) return -1;
  snprintf(out, outlen, "%s/%s", parent_real, slash + 1);
  return 0;
}

/* task_dir must equal, or live strictly under, one allowed prefix. */
static int dir_allowed(const struct tc_config* cfg, const char* task_dir) {
  char dirs[sizeof(cfg->allowed_dirs)];
  char* save = NULL;
  char* tok;
  snprintf(dirs, sizeof(dirs), "%s", cfg->allowed_dirs);
  for (tok = strtok_r(dirs, ",", &save); tok; tok = strtok_r(NULL, ",", &save)) {
    size_t n = strlen(tok);
    if (n == 0 || tok[0] != '/') continue;
    while (n > 1 && tok[n - 1] == '/') tok[--n] = '\0';
    if (strncmp(task_dir, tok, n) == 0 &&
        (task_dir[n] == '\0' || task_dir[n] == '/'))
      return 1;
  }
  return 0;
}

int main(int argc, char** argv) {
  const char* user;
  const char* task_dir;
  const char* logfile;
  struct passwd* pw;
  struct stat st;
  int logfd;
  char* keep_env[64];
  int nkeep = 0;
  int i;

  if (argc < 5) {
    fprintf(stderr,
            "usage: task-controller USER TASK_DIR LOGFILE CMD [ARGS...]\n");
    return 2;
  }
  user = argv[1];
  task_dir = argv[2];
  logfile = argv[3];

  if (validate_path(task_dir) || validate_path(logfile))
    return fail("task dir and logfile must be absolute, no traversal");

  pw = getpwnam(user);
  if (!pw) return fail("unknown target user");

  if (stat(task_dir, &st) || !S_ISDIR(st.st_mode))
    return fail("task dir missing or not a directory");

  if (getuid() == 0) {
    /* production (setuid root): enforce the root-owned policy before
     * touching anything (reference impl/task-controller.c:529-540) */
    static char task_real[PATH_MAX];
    static char log_real[PATH_MAX];
    struct tc_config cfg;
    int rc = load_config(&cfg);
    if (rc) return rc;
    if (pw->pw_uid == 0) return fail("refusing to run tasks as root");
    if ((long)pw->pw_uid < cfg.min_uid)
      return fail("target uid below min.user.id");
    if (user_banned(&cfg, user)) return fail("target user is banned");
    /* resolve symlinks BEFORE the confinement checks — a link planted
     * inside an allowed dir must not smuggle the sandbox outside it */
    if (!realpath(task_dir, task_real))
      return fail("cannot resolve task dir");
    if (resolve_parent(logfile, log_real, sizeof(log_real)))
      return fail("cannot resolve logfile parent");
    task_dir = task_real;
    logfile = log_real;
    if (stat(task_dir, &st) || !S_ISDIR(st.st_mode))
      return fail("resolved task dir missing or not a directory");
    if (!dir_allowed(&cfg, task_dir))
      return fail("task dir not under an allowed local dir");
    if (!dir_allowed(&cfg, logfile))
      return fail("logfile not under an allowed local dir");
    if (st.st_uid != pw->pw_uid)
      return fail("task dir not owned by target user");
    if (setgroups(0, NULL) || setgid(pw->pw_gid) || setuid(pw->pw_uid))
      return fail("cannot drop privileges");
    if (setuid(0) == 0 || getuid() == 0)
      return fail("privilege drop did not stick");
  } else if (getuid() != pw->pw_uid) {
    return fail("not root: target user must be the invoking user");
  }

  /* minimal environment: PATH/HOME/LANG + TPUMR_* passthrough */
  for (i = 0; environ[i] && nkeep < 60; i++) {
    if (strncmp(environ[i], "PATH=", 5) == 0 ||
        strncmp(environ[i], "HOME=", 5) == 0 ||
        strncmp(environ[i], "LANG=", 5) == 0 ||
        strncmp(environ[i], "TPUMR_", 6) == 0)
      keep_env[nkeep++] = environ[i];
  }
  keep_env[nkeep] = NULL;

  if (chdir(task_dir)) return fail("cannot chdir into task dir");

  logfd = open(logfile, O_WRONLY | O_CREAT | O_APPEND | O_NOFOLLOW, 0640);
  if (logfd < 0) return fail("cannot open logfile");
  if (dup2(logfd, 1) < 0 || dup2(logfd, 2) < 0)
    return fail("cannot redirect stdio");
  close(logfd);

  execve(argv[4], &argv[4], keep_env);
  return fail("exec failed");
}
