/* task-controller — privilege-separated task launcher.
 *
 * ≈ the reference's setuid task-controller (src/c++/task-controller/,
 * 2.8k C: the LinuxTaskController backend that launches task processes
 * as the submitting user, with path validation so a compromised tracker
 * cannot aim it outside the task sandbox).
 *
 * Usage: task-controller <user> <task-dir> <stdout-file> <cmd> [args...]
 *
 * - validates the task dir exists, is owned by the invoking/target user,
 *   and contains no ".." traversal;
 * - when running as root (installed setuid, production): setgid/setuid
 *   to the target user before exec;
 * - when not root (tests, single-user clusters): requires <user> to be
 *   the current user and just sandboxes cwd/env;
 * - clears the environment except PATH/HOME/LANG + TPUMR_* passthrough,
 *   chdirs into the task dir, redirects stdout/stderr to the log file,
 *   then execs the command.
 */

#include <errno.h>
#include <fcntl.h>
#include <pwd.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

extern char** environ;

static int fail(const char* msg) {
  fprintf(stderr, "task-controller: %s (errno=%s)\n", msg,
          errno ? strerror(errno) : "0");
  return 10;
}

static int validate_path(const char* p) {
  if (p[0] != '/') return -1;               /* absolute only */
  if (strstr(p, "/../") || strstr(p, "/./")) return -1;
  size_t n = strlen(p);
  if (n >= 3 && strcmp(p + n - 3, "/..") == 0) return -1;
  if (n >= 2 && strcmp(p + n - 2, "/.") == 0) return -1;
  return 0;
}

int main(int argc, char** argv) {
  const char* user;
  const char* task_dir;
  const char* logfile;
  struct passwd* pw;
  struct stat st;
  int logfd;
  char* keep_env[64];
  int nkeep = 0;
  int i;

  if (argc < 5) {
    fprintf(stderr,
            "usage: task-controller USER TASK_DIR LOGFILE CMD [ARGS...]\n");
    return 2;
  }
  user = argv[1];
  task_dir = argv[2];
  logfile = argv[3];

  if (validate_path(task_dir) || validate_path(logfile))
    return fail("task dir and logfile must be absolute, no traversal");

  pw = getpwnam(user);
  if (!pw) return fail("unknown target user");

  if (stat(task_dir, &st) || !S_ISDIR(st.st_mode))
    return fail("task dir missing or not a directory");

  if (getuid() == 0) {
    /* production (setuid root): the sandbox must belong to the target
     * user before we drop into it */
    if (st.st_uid != pw->pw_uid)
      return fail("task dir not owned by target user");
    if (setgid(pw->pw_gid) || setuid(pw->pw_uid))
      return fail("cannot drop privileges");
  } else if (getuid() != pw->pw_uid) {
    return fail("not root: target user must be the invoking user");
  }

  /* minimal environment: PATH/HOME/LANG + TPUMR_* passthrough */
  for (i = 0; environ[i] && nkeep < 60; i++) {
    if (strncmp(environ[i], "PATH=", 5) == 0 ||
        strncmp(environ[i], "HOME=", 5) == 0 ||
        strncmp(environ[i], "LANG=", 5) == 0 ||
        strncmp(environ[i], "TPUMR_", 6) == 0)
      keep_env[nkeep++] = environ[i];
  }
  keep_env[nkeep] = NULL;

  if (chdir(task_dir)) return fail("cannot chdir into task dir");

  logfd = open(logfile, O_WRONLY | O_CREAT | O_APPEND, 0640);
  if (logfd < 0) return fail("cannot open logfile");
  if (dup2(logfd, 1) < 0 || dup2(logfd, 2) < 0)
    return fail("cannot redirect stdio");
  close(logfd);

  execve(argv[4], &argv[4], keep_env);
  return fail("exec failed");
}
