/* fuzz_recio — deterministic fuzz + property checks for the Record I/O
 * binary codec (ASAN/UBSAN enforced, native/sanitize.mk):
 *
 * A: vlong roundtrip across the value space (including the ±112/±120
 *    length-byte boundaries and 8-byte extremes).
 * B: random garbage through recio_validate with a battery of
 *    descriptors — must return -1 or a count, never crash/overrun.
 * C: VALID records (generated from the descriptor) must validate, and
 *    truncations of them must fail cleanly.
 *
 * argv: [iterations]
 */

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

long recio_vlong_write(uint8_t* buf, size_t cap, int64_t v);
long recio_vlong_read(const uint8_t* buf, size_t len, int64_t* out);
int recio_desc_check(const char* desc);
int recio_skip(const uint8_t* buf, size_t len, const char* desc,
               size_t* pos);
long recio_validate(const uint8_t* buf, size_t len, const char* desc);

static uint64_t rng_state = 0x243F6A8885A308D3ull;

static uint64_t rnd(void) {
  uint64_t x = rng_state;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  return rng_state = x;
}

/* advance a descriptor cursor past one type, emitting nothing */
static void desc_skip(const char** d) {
  switch (*(*d)++) {
    case '[':
      desc_skip(d);
      (*d)++;                           /* ']' */
      return;
    case '{':
      desc_skip(d);
      desc_skip(d);
      (*d)++;                           /* '}' */
      return;
    case '(':
      while (**d != ')') desc_skip(d);
      (*d)++;
      return;
    default:
      return;
  }
}

static const char* DESCS[] = {
    "i", "s", "B", "bzifd", "i[s]{bi}", "([i]s)d", "[[i]]",
    "{s{is}}", "(bz(if)s)[B]", "[{i(sz)}]",
};

/* append one VALID value of type **d to buf (advances both) */
static size_t gen_value(uint8_t* buf, size_t cap, size_t pos,
                        const char** d, int depth) {
  if (pos + 64 > cap) {             /* keep headroom; emit minimal */
    depth = 99;
  }
  char t = *(*d)++;
  int64_t n;
  long w;
  switch (t) {
    case 'b':
    case 'z':
      buf[pos++] = (uint8_t)rnd();
      return pos;
    case 'i':
      w = recio_vlong_write(buf + pos, cap - pos,
                            (int64_t)rnd() >> (rnd() % 64));
      return pos + (size_t)w;
    case 'f':
      for (int i = 0; i < 4; i++) buf[pos++] = (uint8_t)rnd();
      return pos;
    case 'd':
      for (int i = 0; i < 8; i++) buf[pos++] = (uint8_t)rnd();
      return pos;
    case 's':
    case 'B':
      n = (depth > 4) ? 0 : (int64_t)(rnd() % 16);
      w = recio_vlong_write(buf + pos, cap - pos, n);
      pos += (size_t)w;
      for (int64_t i = 0; i < n; i++)
        buf[pos++] = (t == 's') ? (uint8_t)('a' + rnd() % 26)
                                : (uint8_t)rnd();
      return pos;
    case '[': {
      n = (depth > 4) ? 0 : (int64_t)(rnd() % 4);
      w = recio_vlong_write(buf + pos, cap - pos, n);
      pos += (size_t)w;
      const char* elem = *d;
      for (int64_t i = 0; i < n; i++) {
        const char* e = elem;
        pos = gen_value(buf, cap, pos, &e, depth + 1);
        *d = e;
      }
      if (n == 0) desc_skip(d);         /* still advance past elem type */
      (*d)++;                           /* ']' */
      return pos;
    }
    case '{': {
      n = (depth > 4) ? 0 : (int64_t)(rnd() % 3);
      w = recio_vlong_write(buf + pos, cap - pos, n);
      pos += (size_t)w;
      const char* kv = *d;
      for (int64_t i = 0; i < n; i++) {
        const char* e = kv;
        pos = gen_value(buf, cap, pos, &e, depth + 1);
        pos = gen_value(buf, cap, pos, &e, depth + 1);
        *d = e;
      }
      if (n == 0) {
        desc_skip(d);
        desc_skip(d);
      }
      (*d)++;                           /* '}' */
      return pos;
    }
    case '(': {
      while (**d != ')') pos = gen_value(buf, cap, pos, d, depth + 1);
      (*d)++;
      return pos;
    }
    default:
      fprintf(stderr, "gen: bad descriptor char %c\n", t);
      exit(2);
  }
}

int main(int argc, char** argv) {
  long iters = argc > 1 ? atol(argv[1]) : 2000;
  uint8_t buf[4096];
  int64_t v, back;

  /* A: vlong roundtrip */
  for (long it = 0; it < iters; it++) {
    v = (int64_t)rnd() >> (rnd() % 64);
    long w = recio_vlong_write(buf, sizeof buf, v);
    if (w < 1 || recio_vlong_read(buf, (size_t)w, &back) != w ||
        back != v) {
      fprintf(stderr, "vlong roundtrip failed for %lld\n",
              (long long)v);
      return 1;
    }
  }
  int64_t edges[] = {0, 127, 128, -112, -113, 255, 256, -129,
                     (int64_t)1 << 62, -((int64_t)1 << 62),
                     INT64_MAX, INT64_MIN};
  for (size_t i = 0; i < sizeof edges / sizeof *edges; i++) {
    long w = recio_vlong_write(buf, sizeof buf, edges[i]);
    if (w < 1 || recio_vlong_read(buf, (size_t)w, &back) != w ||
        back != edges[i]) {
      fprintf(stderr, "vlong edge failed\n");
      return 1;
    }
  }

  size_t ndesc = sizeof DESCS / sizeof *DESCS;
  for (size_t i = 0; i < ndesc; i++) {
    if (recio_desc_check(DESCS[i]) != 0) {
      fprintf(stderr, "descriptor %s rejected\n", DESCS[i]);
      return 1;
    }
  }

  /* B: garbage in -> no crash */
  for (long it = 0; it < iters; it++) {
    size_t n = rnd() % sizeof buf;
    for (size_t i = 0; i < n; i++) buf[i] = (uint8_t)rnd();
    (void)recio_validate(buf, n, DESCS[rnd() % ndesc]);
  }

  /* C: valid records validate; truncations fail cleanly */
  for (long it = 0; it < iters; it++) {
    const char* desc = DESCS[rnd() % ndesc];
    const char* d = desc;
    size_t n = 0;
    while (*d) n = gen_value(buf, sizeof buf, n, &d, 0);
    if (recio_validate(buf, n, desc) != 1) {
      fprintf(stderr, "valid record rejected (desc %s, %zu bytes)\n",
              desc, n);
      return 1;
    }
    if (n > 1) {
      size_t cut = 1 + rnd() % (n - 1);
      long r = recio_validate(buf, cut, desc);
      (void)r;                        /* -1 or short count, NO crash */
    }
  }
  printf("recio fuzz clean (%ld iterations)\n", iters);
  return 0;
}
