/* recio — C codec for the Record I/O BINARY wire format.
 *
 * The librecordio role (reference: src/c++/librecordio, 3.8k C++ whose
 * heart is the binary archive): lets non-Python consumers write and
 * validate record streams produced by tpumr/recordio/runtime.py
 * (BinaryRecordOutput: Hadoop zero-compressed vlongs, big-endian IEEE
 * float/double, vlong-length-prefixed UTF-8 strings and buffers,
 * size-prefixed vectors/maps, structs flat).
 *
 * Instead of generated per-record C++ classes, records are described by
 * a DESCRIPTOR string — the same idea as the Python tier's declarative
 * FIELDS, one char per field:
 *
 *   b byte   z boolean   i int/long (vlong)   f float   d double
 *   s ustring   B buffer   [e] vector of e   {kv} map of k->v
 *   (fields...) nested record
 *
 * e.g. the DDL  class R { int a; vector<ustring> v; map<byte,long> m; }
 * has descriptor "i[s]{bi}".
 *
 * API (all bounds-checked; never reads past len):
 *   recio_vlong_write(buf, cap, val)         -> bytes written or -1
 *   recio_vlong_read(buf, len, *val)         -> bytes consumed or -1
 *   recio_skip(buf, len, desc, *pos)         -> 0 ok, -1 malformed
 *   recio_validate(buf, len, desc)           -> #complete records, -1 bad
 *   recio_desc_check(desc)                   -> 0 well-formed, -1 not
 */

#include <stdint.h>
#include <stddef.h>
#include <string.h>

long recio_vlong_write(uint8_t* buf, size_t cap, int64_t v) {
  if (v >= -112 && v <= 127) {
    if (cap < 1) return -1;
    buf[0] = (uint8_t)v;
    return 1;
  }
  int len = -112;
  uint64_t u;
  if (v < 0) {
    u = (uint64_t)(~v);
    len = -120;
  } else {
    u = (uint64_t)v;
  }
  uint64_t tmp = u;
  while (tmp) {
    tmp >>= 8;
    len--;
  }
  int n = (len < -120) ? -(len + 120) : -(len + 112);
  if (cap < (size_t)(n + 1)) return -1;
  buf[0] = (uint8_t)len;
  for (int idx = n; idx != 0; idx--)
    buf[n - idx + 1] = (uint8_t)(u >> ((idx - 1) * 8));
  return n + 1;
}

long recio_vlong_read(const uint8_t* buf, size_t len, int64_t* out) {
  if (len < 1) return -1;
  int8_t first = (int8_t)buf[0];
  if (first >= -112) {
    *out = first;
    return 1;
  }
  int n = (first < -120) ? (-119 - first) : (-111 - first);
  if (n < 2 || n > 9 || len < (size_t)n) return -1;
  uint64_t u = 0;
  for (int i = 1; i < n; i++) u = (u << 8) | buf[i];
  *out = (first < -120) ? (int64_t)~u : (int64_t)u;
  return n;
}

/* ------------------------------------------------------- descriptors */

/* advance *d past one type element; -1 if malformed */
static int desc_next(const char** d) {
  switch (**d) {
    case 'b': case 'z': case 'i': case 'f': case 'd':
    case 's': case 'B':
      (*d)++;
      return 0;
    case '[':
      (*d)++;
      if (desc_next(d) != 0 || **d != ']') return -1;
      (*d)++;
      return 0;
    case '{':
      (*d)++;
      if (desc_next(d) != 0 || desc_next(d) != 0 || **d != '}') return -1;
      (*d)++;
      return 0;
    case '(':
      (*d)++;
      while (**d && **d != ')')
        if (desc_next(d) != 0) return -1;
      if (**d != ')') return -1;
      (*d)++;
      return 0;
    default:
      return -1;
  }
}

int recio_desc_check(const char* desc) {
  const char* d = desc;
  while (*d)
    if (desc_next(&d) != 0) return -1;
  return 0;
}

/* skip one value of type **d (advancing both cursors); -1 malformed */
static int skip_value(const uint8_t* buf, size_t len, size_t* pos,
                      const char** d, int depth) {
  if (depth > 64) return -1;              /* descriptor bombs */
  int64_t v;
  long n;
  char t = **d;
  switch (t) {
    case 'b':
    case 'z':
      (*d)++;
      if (*pos + 1 > len) return -1;
      (*pos)++;
      return 0;
    case 'i':
      (*d)++;
      n = recio_vlong_read(buf + *pos, len - *pos, &v);
      if (n < 0) return -1;
      *pos += (size_t)n;
      return 0;
    case 'f':
    case 'd': {
      (*d)++;
      size_t w = (t == 'f') ? 4 : 8;
      if (*pos + w > len) return -1;
      *pos += w;
      return 0;
    }
    case 's':
    case 'B':
      (*d)++;
      n = recio_vlong_read(buf + *pos, len - *pos, &v);
      if (n < 0 || v < 0) return -1;
      *pos += (size_t)n;
      if ((uint64_t)v > len - *pos) return -1;
      *pos += (size_t)v;
      return 0;
    case '[': {
      (*d)++;
      n = recio_vlong_read(buf + *pos, len - *pos, &v);
      if (n < 0 || v < 0) return -1;
      *pos += (size_t)n;
      const char* elem = *d;
      for (int64_t i = 0; i < v; i++) {
        const char* e = elem;
        size_t before = *pos;
        if (skip_value(buf, len, pos, &e, depth + 1) != 0) return -1;
        if (*pos == before) break;  /* zero-width element (empty struct):
                                     * every remaining iteration is also
                                     * zero bytes — an attacker count of
                                     * 2^62 must not become 2^62 spins */
      }
      if (desc_next(d) != 0 || **d != ']') return -1;
      (*d)++;
      return 0;
    }
    case '{': {
      (*d)++;
      n = recio_vlong_read(buf + *pos, len - *pos, &v);
      if (n < 0 || v < 0) return -1;
      *pos += (size_t)n;
      const char* kv = *d;
      for (int64_t i = 0; i < v; i++) {
        const char* e = kv;
        size_t before = *pos;
        if (skip_value(buf, len, pos, &e, depth + 1) != 0) return -1;
        if (skip_value(buf, len, pos, &e, depth + 1) != 0) return -1;
        if (*pos == before) break;  /* zero-width pair: same DoS guard
                                     * as the vector case */
      }
      if (desc_next(d) != 0 || desc_next(d) != 0 || **d != '}')
        return -1;
      (*d)++;
      return 0;
    }
    case '(':
      (*d)++;
      while (**d && **d != ')')
        if (skip_value(buf, len, pos, d, depth + 1) != 0) return -1;
      if (**d != ')') return -1;
      (*d)++;
      return 0;
    default:
      return -1;
  }
}

int recio_skip(const uint8_t* buf, size_t len, const char* desc,
               size_t* pos) {
  const char* d = desc;
  while (*d)
    if (skip_value(buf, len, pos, &d, 0) != 0) return -1;
  return 0;
}

long recio_validate(const uint8_t* buf, size_t len, const char* desc) {
  if (recio_desc_check(desc) != 0) return -1;
  size_t pos = 0;
  long count = 0;
  while (pos < len) {
    size_t before = pos;
    if (recio_skip(buf, len, desc, &pos) != 0) return -1;
    if (pos == before) return -1;       /* empty descriptor: no progress */
    count++;
  }
  return count;
}
