// tpumr pipes — C++ child-side user API.
//
// ≈ the reference C++ pipes API (src/c++/pipes/api/hadoop/Pipes.hh:46-247:
// JobConf / TaskContext / Mapper / Reducer / Factory / runTask). A pipes
// executable links this library, defines a Factory, and calls
// tpumr::pipes::runTask(factory). The framework (tpumr.pipes.application)
// launches the binary and speaks the framed varint protocol over a loopback
// socket; an accelerator task receives its device id as argv[1]
// (≈ Application.java:178-181).
#ifndef TPUMR_PIPES_HH
#define TPUMR_PIPES_HH

#include <cstdint>
#include <map>
#include <string>

namespace tpumr {
namespace pipes {

class JobConf {
 public:
  bool hasKey(const std::string& key) const;
  const std::string& get(const std::string& key) const;
  int getInt(const std::string& key, int def = 0) const;
  float getFloat(const std::string& key, float def = 0.0f) const;
  bool getBoolean(const std::string& key, bool def = false) const;
  std::map<std::string, std::string> items;
};

class TaskContext {
 public:
  virtual ~TaskContext() {}
  virtual const JobConf* getJobConf() = 0;
  virtual const std::string& getInputKey() = 0;
  virtual const std::string& getInputValue() = 0;
  virtual const std::string& getInputSplit() = 0;
  virtual void emit(const std::string& key, const std::string& value) = 0;
  virtual void partitionedEmit(int partition, const std::string& key,
                               const std::string& value) = 0;
  virtual void progress(double value) = 0;
  virtual void setStatus(const std::string& status) = 0;
  virtual int getCounter(const std::string& group,
                         const std::string& name) = 0;
  virtual void incrementCounter(int counterId, uint64_t amount) = 0;
  // reduce side: advance the value cursor; false at end of key group
  virtual bool nextValue() = 0;
  // map side: reduce count of the job (for custom partitioners)
  virtual int getNumReduces() = 0;
};

class Mapper {
 public:
  virtual ~Mapper() {}
  virtual void map(TaskContext& context) = 0;
  virtual void close() {}
};

class Reducer {
 public:
  virtual ~Reducer() {}
  // called once per key group; iterate values with context.nextValue()
  virtual void reduce(TaskContext& context) = 0;
  virtual void close() {}
};

class Partitioner {
 public:
  virtual ~Partitioner() {}
  // ≈ Pipes.hh Partitioner::partition: route a map output key to a
  // reduce; the runtime then ships PARTITIONED_OUTPUT frames and the
  // framework's PipesPartitioner honors the child's choice
  virtual int partition(const std::string& key, int numReduces) = 0;
};

class Factory {
 public:
  virtual ~Factory() {}
  virtual Mapper* createMapper(TaskContext& context) const = 0;
  virtual Reducer* createReducer(TaskContext& context) const = 0;
  // optional: NULL (the default) = framework-side hash partitioning
  virtual Partitioner* createPartitioner(TaskContext&) const { return 0; }
};

// Child entry point (≈ HadoopPipes::runTask). Returns the process exit code.
int runTask(const Factory& factory);

}  // namespace pipes
}  // namespace tpumr

#endif  // TPUMR_PIPES_HH
