// Pipes wordcount with a C++ partitioner.
// ≈ src/examples/pipes/impl/wordcount-part.cc: the child routes each map
// output to a reduce itself (PARTITIONED_OUTPUT frames); the framework's
// PipesPartitioner honors the child's choice, so custom routing logic can
// live entirely in the user binary.

#include <cstdio>
#include <cstdlib>
#include <string>

#include "../tpumr_pipes.hh"

using tpumr::pipes::Factory;
using tpumr::pipes::Mapper;
using tpumr::pipes::Partitioner;
using tpumr::pipes::Reducer;
using tpumr::pipes::TaskContext;

class WordCountMapper : public Mapper {
 public:
  explicit WordCountMapper(TaskContext&) {}
  void map(TaskContext& ctx) {
    const std::string& line = ctx.getInputValue();
    size_t i = 0;
    while (i < line.size()) {
      while (i < line.size() && isspace(static_cast<unsigned char>(line[i])))
        i++;
      size_t start = i;
      while (i < line.size() && !isspace(static_cast<unsigned char>(line[i])))
        i++;
      if (i > start) ctx.emit(line.substr(start, i - start), "1");
    }
  }
};

class SumReducer : public Reducer {
 public:
  explicit SumReducer(TaskContext&) {}
  void reduce(TaskContext& ctx) {
    long long sum = 0;
    while (ctx.nextValue()) sum += atoll(ctx.getInputValue().c_str());
    char buf[32];
    snprintf(buf, sizeof(buf), "%lld", sum);
    ctx.emit(ctx.getInputKey(), buf);
  }
};

// first-byte partitioner (same idea as the reference's WordCountPartitioner:
// a deliberately observable, deterministic routing rule)
class FirstBytePartitioner : public Partitioner {
 public:
  int partition(const std::string& key, int numReduces) {
    if (key.empty() || numReduces <= 0) return 0;
    return static_cast<unsigned char>(key[0]) % numReduces;
  }
};

class WordCountPartFactory : public Factory {
 public:
  Mapper* createMapper(TaskContext& ctx) const {
    return new WordCountMapper(ctx);
  }
  Reducer* createReducer(TaskContext& ctx) const {
    return new SumReducer(ctx);
  }
  Partitioner* createPartitioner(TaskContext&) const {
    return new FirstBytePartitioner();
  }
};

int main(int argc, char** argv) {
  if (argc > 1)
    fprintf(stderr, "wordcount-part: bound to device %s\n", argv[1]);
  WordCountPartFactory factory;
  return tpumr::pipes::runTask(factory);
}
