// Pipes sort — identity mapper/reducer.
// ≈ src/examples/pipes/impl/sort.cc: the binary just passes records
// through; the framework's sort/shuffle between map and reduce does the
// actual ordering. Useful as the minimal pipes program and as a
// shuffle-path exerciser from an external child.

#include <cstdio>

#include "../tpumr_pipes.hh"

using tpumr::pipes::Factory;
using tpumr::pipes::Mapper;
using tpumr::pipes::Reducer;
using tpumr::pipes::TaskContext;

class IdentityMapper : public Mapper {
 public:
  explicit IdentityMapper(TaskContext&) {}
  void map(TaskContext& ctx) {
    // key on the line content so the framework sorts by it
    ctx.emit(ctx.getInputValue(), "");
  }
};

class IdentityReducer : public Reducer {
 public:
  explicit IdentityReducer(TaskContext&) {}
  void reduce(TaskContext& ctx) {
    while (ctx.nextValue())
      ctx.emit(ctx.getInputKey(), ctx.getInputValue());
  }
};

class SortFactory : public Factory {
 public:
  Mapper* createMapper(TaskContext& ctx) const {
    return new IdentityMapper(ctx);
  }
  Reducer* createReducer(TaskContext& ctx) const {
    return new IdentityReducer(ctx);
  }
};

int main(int argc, char** argv) {
  if (argc > 1) fprintf(stderr, "sort: bound to device %s\n", argv[1]);
  SortFactory factory;
  return tpumr::pipes::runTask(factory);
}
