// Pipes wordcount — the canonical external-binary example.
// ≈ the reference pipes demo (src/examples/pipes/impl/wordcount-simple.cc),
// written against the tpumr C++ API. An accelerator build of this binary
// would read its device id from argv[1] (≈ Application.java:178-181); here
// we just report the binding so the dual-executable path is observable.

#include <cstdio>
#include <cstdlib>
#include <string>

#include "../tpumr_pipes.hh"

using tpumr::pipes::Factory;
using tpumr::pipes::Mapper;
using tpumr::pipes::Reducer;
using tpumr::pipes::TaskContext;

class WordCountMapper : public Mapper {
 public:
  explicit WordCountMapper(TaskContext& ctx) {
    inputWords_ = ctx.getCounter("WordCount", "INPUT_WORDS");
  }
  void map(TaskContext& ctx) {
    const std::string& line = ctx.getInputValue();
    size_t i = 0;
    int n = 0;
    while (i < line.size()) {
      while (i < line.size() && isspace(static_cast<unsigned char>(line[i])))
        i++;
      size_t start = i;
      while (i < line.size() && !isspace(static_cast<unsigned char>(line[i])))
        i++;
      if (i > start) {
        ctx.emit(line.substr(start, i - start), "1");
        n++;
      }
    }
    if (n) ctx.incrementCounter(inputWords_, uint64_t(n));
  }

 private:
  int inputWords_;
};

class SumReducer : public Reducer {
 public:
  explicit SumReducer(TaskContext&) {}
  void reduce(TaskContext& ctx) {
    long long sum = 0;
    while (ctx.nextValue())
      sum += atoll(ctx.getInputValue().c_str());
    char buf[32];
    snprintf(buf, sizeof(buf), "%lld", sum);
    ctx.emit(ctx.getInputKey(), buf);
  }
};

class WordCountFactory : public Factory {
 public:
  Mapper* createMapper(TaskContext& ctx) const {
    return new WordCountMapper(ctx);
  }
  Reducer* createReducer(TaskContext& ctx) const {
    return new SumReducer(ctx);
  }
};

int main(int argc, char** argv) {
  if (argc > 1)  // accelerator launch: device id as argv[1]
    fprintf(stderr, "wordcount: bound to device %s\n", argv[1]);
  WordCountFactory factory;
  return tpumr::pipes::runTask(factory);
}
