// Pipes wordcount with the child's OWN record reader (non-piped input).
// ≈ src/examples/pipes/impl/wordcount-nopipe.cc: with
// tpumr.pipes.piped.input=false the framework sends RUN_MAP with the
// split description and NO per-record frames — the child parses the
// split JSON, opens the file itself, and reads exactly its byte range.
// This is the "bring your own reader" capability: record parsing costs
// stay in native code and nothing crosses the pipe until output.

#include <cstdio>
#include <cstdlib>
#include <stdexcept>
#include <string>

#include "../tpumr_pipes.hh"

using tpumr::pipes::Factory;
using tpumr::pipes::Mapper;
using tpumr::pipes::Reducer;
using tpumr::pipes::TaskContext;

// minimal extraction from the split JSON ({"path": "file://...",
// "start": N, "split_length": N, ...}); a real deployment would link a
// JSON library — the demo keeps the binary dependency-free
static std::string jsonString(const std::string& js, const std::string& k) {
  std::string needle = "\"" + k + "\"";
  size_t p = js.find(needle);
  if (p == std::string::npos) return "";
  p = js.find('"', p + needle.size() + 1);
  if (p == std::string::npos) return "";
  size_t e = js.find('"', p + 1);
  return js.substr(p + 1, e - p - 1);
}

static long long jsonNumber(const std::string& js, const std::string& k) {
  std::string needle = "\"" + k + "\"";
  size_t p = js.find(needle);
  if (p == std::string::npos) return 0;
  p = js.find(':', p);
  return atoll(js.c_str() + p + 1);
}

class NoPipeMapper : public Mapper {
 public:
  explicit NoPipeMapper(TaskContext&) {}

  void map(TaskContext& ctx) {
    const std::string& split = ctx.getInputSplit();
    std::string path = jsonString(split, "path");
    long long start = jsonNumber(split, "start");
    long long length = jsonNumber(split, "split_length");
    if (path.rfind("file://", 0) == 0) path = path.substr(7);
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) {
      ctx.setStatus("cannot open " + path);
      throw std::runtime_error("wordcount-nopipe: cannot open input");
    }
    // line-split contract of the framework's own TextInputFormat: a
    // non-zero start skips the partial first line (the previous split
    // owns it); read through the line crossing the end boundary
    if (start > 0) {
      fseek(f, start - 1, SEEK_SET);
      int c;
      while ((c = fgetc(f)) != EOF && c != '\n') {}
    } else {
      fseek(f, 0, SEEK_SET);
    }
    long long limit = start + length;
    for (;;) {
      // a line belongs to this split iff it STARTS inside [start, limit)
      if (ftell(f) >= limit) break;
      std::string line;
      int c;
      while ((c = fgetc(f)) != EOF && c != '\n') line.push_back(char(c));
      size_t i = 0;
      while (i < line.size()) {
        while (i < line.size() &&
               isspace(static_cast<unsigned char>(line[i])))
          i++;
        size_t w = i;
        while (i < line.size() &&
               !isspace(static_cast<unsigned char>(line[i])))
          i++;
        if (i > w) ctx.emit(line.substr(w, i - w), "1");
      }
      if (c == EOF) break;
    }
    fclose(f);
  }
};

class SumReducer : public Reducer {
 public:
  explicit SumReducer(TaskContext&) {}
  void reduce(TaskContext& ctx) {
    long long sum = 0;
    while (ctx.nextValue()) sum += atoll(ctx.getInputValue().c_str());
    char buf[32];
    snprintf(buf, sizeof(buf), "%lld", sum);
    ctx.emit(ctx.getInputKey(), buf);
  }
};

class NoPipeFactory : public Factory {
 public:
  Mapper* createMapper(TaskContext& ctx) const {
    return new NoPipeMapper(ctx);
  }
  Reducer* createReducer(TaskContext& ctx) const {
    return new SumReducer(ctx);
  }
};

int main(int argc, char** argv) {
  if (argc > 1)
    fprintf(stderr, "wordcount-nopipe: bound to device %s\n", argv[1]);
  NoPipeFactory factory;
  return tpumr::pipes::runTask(factory);
}
