// tpumr pipes — C++ child runtime: socket transport, framed varint
// protocol, HMAC-SHA1 authentication, task event loop.
//
// ≈ the reference child runtime (src/c++/pipes/impl/HadoopPipes.cc:296 —
// protocol binding — and :475-546 — the event loop), re-designed around the
// tpumr wire format (unsigned LEB128 varints, length-prefixed bytes,
// big-endian IEEE doubles; codes in tpumr/pipes/protocol.py).

#include "tpumr_pipes.hh"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <stdexcept>
#include <vector>

namespace tpumr {
namespace pipes {

// ------------------------------------------------------------------ codes
enum Downward {
  START = 0, SET_JOB_CONF = 1, SET_INPUT_TYPES = 2, RUN_MAP = 3,
  MAP_ITEM = 4, RUN_REDUCE = 5, REDUCE_KEY = 6, REDUCE_VALUE = 7,
  CLOSE = 8, ABORT = 9, AUTHENTICATION_REQ = 10,
};
enum Upward {
  OUTPUT = 50, PARTITIONED_OUTPUT = 51, STATUS = 52, PROGRESS = 53,
  DONE = 54, REGISTER_COUNTER = 55, INCREMENT_COUNTER = 56,
  AUTHENTICATION_RESP = 57,
};
static const uint64_t PROTOCOL_VERSION = 0;

// ------------------------------------------------------------------ sha1
// Compact SHA-1 (FIPS 180-1) for the auth handshake only — the data plane
// never hashes.
struct Sha1 {
  uint32_t h[5];
  uint64_t len;
  unsigned char buf[64];
  size_t fill;

  Sha1() { reset(); }
  void reset() {
    h[0] = 0x67452301; h[1] = 0xEFCDAB89; h[2] = 0x98BADCFE;
    h[3] = 0x10325476; h[4] = 0xC3D2E1F0;
    len = 0; fill = 0;
  }
  static uint32_t rol(uint32_t x, int n) {
    return (x << n) | (x >> (32 - n));
  }
  void block(const unsigned char* p) {
    uint32_t w[80];
    for (int i = 0; i < 16; i++)
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 80; i++)
      w[i] = rol(w[i - 3] ^ w[i - 8] ^ w[i - 14] ^ w[i - 16], 1);
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4];
    for (int i = 0; i < 80; i++) {
      uint32_t f, k;
      if (i < 20)      { f = (b & c) | (~b & d);            k = 0x5A827999; }
      else if (i < 40) { f = b ^ c ^ d;                     k = 0x6ED9EBA1; }
      else if (i < 60) { f = (b & c) | (b & d) | (c & d);   k = 0x8F1BBCDC; }
      else             { f = b ^ c ^ d;                     k = 0xCA62C1D6; }
      uint32_t t = rol(a, 5) + f + e + k + w[i];
      e = d; d = c; c = rol(b, 30); b = a; a = t;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d; h[4] += e;
  }
  void update(const void* data, size_t n) {
    const unsigned char* p = static_cast<const unsigned char*>(data);
    len += n;
    while (n > 0) {
      size_t take = 64 - fill;
      if (take > n) take = n;
      memcpy(buf + fill, p, take);
      fill += take; p += take; n -= take;
      if (fill == 64) { block(buf); fill = 0; }
    }
  }
  void final(unsigned char out[20]) {
    uint64_t bits = len * 8;
    unsigned char pad = 0x80;
    update(&pad, 1);
    unsigned char zero = 0;
    while (fill != 56) update(&zero, 1);
    unsigned char lenb[8];
    for (int i = 0; i < 8; i++) lenb[i] = (bits >> (56 - 8 * i)) & 0xFF;
    update(lenb, 8);
    for (int i = 0; i < 5; i++) {
      out[4 * i] = (h[i] >> 24) & 0xFF;
      out[4 * i + 1] = (h[i] >> 16) & 0xFF;
      out[4 * i + 2] = (h[i] >> 8) & 0xFF;
      out[4 * i + 3] = h[i] & 0xFF;
    }
  }
};

static std::string hmacSha1Hex(const std::string& key,
                               const std::string& msg) {
  unsigned char k[64];
  memset(k, 0, sizeof(k));
  if (key.size() > 64) {
    Sha1 s; s.update(key.data(), key.size());
    unsigned char d[20]; s.final(d);
    memcpy(k, d, 20);
  } else {
    memcpy(k, key.data(), key.size());
  }
  unsigned char ipad[64], opad[64];
  for (int i = 0; i < 64; i++) { ipad[i] = k[i] ^ 0x36; opad[i] = k[i] ^ 0x5C; }
  Sha1 inner; inner.update(ipad, 64); inner.update(msg.data(), msg.size());
  unsigned char id[20]; inner.final(id);
  Sha1 outer; outer.update(opad, 64); outer.update(id, 20);
  unsigned char od[20]; outer.final(od);
  static const char* hex = "0123456789abcdef";
  std::string out(40, '0');
  for (int i = 0; i < 20; i++) {
    out[2 * i] = hex[od[i] >> 4];
    out[2 * i + 1] = hex[od[i] & 0xF];
  }
  return out;
}

// ------------------------------------------------------------------ stream
class SocketStream {
 public:
  // sanity bound on one length-prefixed byte string; key/value/split
  // payloads are far smaller (the framework streams large data)
  static const uint64_t kMaxBytes = 256ull * 1024 * 1024;

  explicit SocketStream(int fd) : fd_(fd), rpos_(0), rlen_(0) {}

  uint64_t readVarint() {
    uint64_t result = 0;
    int shift = 0;
    for (;;) {
      int b = readByte();
      result |= uint64_t(b & 0x7F) << shift;
      if (!(b & 0x80)) return result;
      shift += 7;
      if (shift > 63) throw std::runtime_error("varint too long");
    }
  }
  std::string readBytes() {
    uint64_t n = readVarint();
    // the length is untrusted wire data: cap it before the allocation
    // (a hostile/corrupt parent could otherwise drive a 2^63 resize)
    if (n > kMaxBytes)
      throw std::runtime_error("pipes frame too large");
    std::string out(n, '\0');
    readFully(&out[0], n);
    return out;
  }
  double readDouble() {
    unsigned char b[8];
    readFully(reinterpret_cast<char*>(b), 8);
    uint64_t bits = 0;
    for (int i = 0; i < 8; i++) bits = (bits << 8) | b[i];
    double d;
    memcpy(&d, &bits, 8);
    return d;
  }

  void writeVarint(uint64_t n) {
    unsigned char tmp[10];
    int len = 0;
    do {
      unsigned char b = n & 0x7F;
      n >>= 7;
      if (n) b |= 0x80;
      tmp[len++] = b;
    } while (n);
    wbuf_.insert(wbuf_.end(), tmp, tmp + len);
  }
  void writeBytes(const std::string& s) {
    writeVarint(s.size());
    wbuf_.insert(wbuf_.end(), s.begin(), s.end());
  }
  void writeDouble(double d) {
    uint64_t bits;
    memcpy(&bits, &d, 8);
    for (int i = 7; i >= 0; i--)
      wbuf_.push_back(static_cast<char>((bits >> (8 * i)) & 0xFF));
  }
  void flush() {
    size_t off = 0;
    while (off < wbuf_.size()) {
      ssize_t n = ::write(fd_, wbuf_.data() + off, wbuf_.size() - off);
      if (n <= 0) throw std::runtime_error("pipes socket write failed");
      off += size_t(n);
    }
    wbuf_.clear();
  }
  // bounded buffering: emit-heavy tasks must stream, not accumulate the
  // whole task output in memory
  void maybeFlush() {
    if (wbuf_.size() >= 64 * 1024) flush();
  }

 private:
  int readByte() {
    if (rpos_ == rlen_) {
      ssize_t n = ::read(fd_, rbuf_, sizeof(rbuf_));
      if (n <= 0) throw std::runtime_error("pipes socket closed");
      rlen_ = size_t(n);
      rpos_ = 0;
    }
    return static_cast<unsigned char>(rbuf_[rpos_++]);
  }
  void readFully(char* dst, size_t n) {
    for (size_t i = 0; i < n; i++)
      dst[i] = static_cast<char>(readByte());
  }

  int fd_;
  char rbuf_[65536];
  size_t rpos_, rlen_;
  std::vector<char> wbuf_;
};

// ------------------------------------------------------------------ conf
bool JobConf::hasKey(const std::string& key) const {
  return items.count(key) != 0;
}
const std::string& JobConf::get(const std::string& key) const {
  static const std::string empty;
  std::map<std::string, std::string>::const_iterator it = items.find(key);
  return it == items.end() ? empty : it->second;
}
int JobConf::getInt(const std::string& key, int def) const {
  return hasKey(key) ? atoi(get(key).c_str()) : def;
}
float JobConf::getFloat(const std::string& key, float def) const {
  return hasKey(key) ? float(atof(get(key).c_str())) : def;
}
bool JobConf::getBoolean(const std::string& key, bool def) const {
  if (!hasKey(key)) return def;
  const std::string& v = get(key);
  return v == "true" || v == "True" || v == "1";
}

// ------------------------------------------------------------------ loop
class TaskRunner : public TaskContext {
 public:
  TaskRunner(const Factory& factory, SocketStream& io)
      : factory_(factory), io_(io), nextCounter_(0), numReduces_(0),
        havePendingKey_(false), closed_(false) {}

  int run() {
    std::unique_ptr<Mapper> mapper;
    std::unique_ptr<Reducer> reducer;
    std::unique_ptr<Partitioner> partitioner;
    for (;;) {
      uint64_t code = io_.readVarint();
      if (code == START) {
        if (io_.readVarint() != PROTOCOL_VERSION)
          throw std::runtime_error("protocol version mismatch");
      } else if (code == SET_JOB_CONF) {
        uint64_t n = io_.readVarint();
        for (uint64_t i = 0; i < n; i++) {
          std::string k = io_.readBytes();
          conf_.items[k] = io_.readBytes();
        }
      } else if (code == SET_INPUT_TYPES) {
        io_.readBytes();
        io_.readBytes();
      } else if (code == RUN_MAP) {
        split_ = io_.readBytes();
        numReduces_ = int(io_.readVarint());
        uint64_t pipedInput = io_.readVarint();
        mapper.reset(factory_.createMapper(*this));
        partitioner.reset(factory_.createPartitioner(*this));
        partitioner_ = partitioner.get();
        if (!pipedInput) {
          // non-piped input (≈ wordcount-nopipe / isJavaInput=false,
          // Submitter's own-reader mode): the child reads the split
          // itself — one map() call over the whole split, no MAP_ITEMs
          mapper->map(*this);
        }
      } else if (code == MAP_ITEM) {
        key_ = io_.readBytes();
        value_ = io_.readBytes();
        mapper->map(*this);
      } else if (code == RUN_REDUCE) {
        io_.readVarint();  // partition
        io_.readVarint();  // piped output
        reducer.reset(factory_.createReducer(*this));
      } else if (code == REDUCE_KEY) {
        pendingKey_ = io_.readBytes();
        havePendingKey_ = true;
        while (havePendingKey_ && !closed_) {
          key_ = pendingKey_;
          havePendingKey_ = false;
          reducer->reduce(*this);
          while (nextValue()) {}  // drain unconsumed values
        }
        if (closed_) break;
      } else if (code == CLOSE) {
        break;
      } else if (code == ABORT) {
        return 1;
      } else {
        throw std::runtime_error("unknown downward opcode");
      }
    }
    if (mapper.get()) mapper->close();
    if (reducer.get()) reducer->close();
    io_.writeVarint(DONE);
    io_.flush();
    return 0;
  }

  void authenticate(const std::string& secret) {
    if (io_.readVarint() != AUTHENTICATION_REQ)
      throw std::runtime_error("expected auth request");
    std::string digest = io_.readBytes();
    std::string challenge = io_.readBytes();
    if (digest != hmacSha1Hex(secret, "CLIENT-AUTH"))
      throw std::runtime_error("framework failed authentication");
    io_.writeVarint(AUTHENTICATION_RESP);
    io_.writeBytes(hmacSha1Hex(secret, challenge));
    io_.flush();
  }

  // -------------------------------------------------- TaskContext
  const JobConf* getJobConf() { return &conf_; }
  const std::string& getInputKey() { return key_; }
  const std::string& getInputValue() { return value_; }
  const std::string& getInputSplit() { return split_; }
  void emit(const std::string& key, const std::string& value) {
    // a user partitioner routes map output itself (≈ HadoopPipes.cc:
    // emit via partitioned writer when a partitioner is defined)
    if (partitioner_ && numReduces_ > 0) {
      partitionedEmit(partitioner_->partition(key, numReduces_),
                      key, value);
      return;
    }
    io_.writeVarint(OUTPUT);
    io_.writeBytes(key);
    io_.writeBytes(value);
    io_.maybeFlush();
  }
  void partitionedEmit(int partition, const std::string& key,
                       const std::string& value) {
    io_.writeVarint(PARTITIONED_OUTPUT);
    io_.writeVarint(uint64_t(partition));
    io_.writeBytes(key);
    io_.writeBytes(value);
    io_.maybeFlush();
  }
  void progress(double value) {
    io_.writeVarint(PROGRESS);
    io_.writeDouble(value);
    io_.flush();
  }
  void setStatus(const std::string& status) {
    io_.writeVarint(STATUS);
    io_.writeBytes(status);
    io_.flush();
  }
  int getCounter(const std::string& group, const std::string& name) {
    int id = nextCounter_++;
    io_.writeVarint(REGISTER_COUNTER);
    io_.writeVarint(uint64_t(id));
    io_.writeBytes(group);
    io_.writeBytes(name);
    return id;
  }
  void incrementCounter(int counterId, uint64_t amount) {
    io_.writeVarint(INCREMENT_COUNTER);
    io_.writeVarint(uint64_t(counterId));
    io_.writeVarint(amount);
    io_.maybeFlush();
  }
  bool nextValue() {
    if (havePendingKey_ || closed_) return false;
    uint64_t code = io_.readVarint();
    if (code == REDUCE_VALUE) {
      value_ = io_.readBytes();
      return true;
    }
    if (code == REDUCE_KEY) {
      pendingKey_ = io_.readBytes();
      havePendingKey_ = true;
      return false;
    }
    if (code == CLOSE) {
      closed_ = true;
      return false;
    }
    throw std::runtime_error("unexpected opcode inside reduce");
  }

  int getNumReduces() { return numReduces_; }

 private:
  const Factory& factory_;
  SocketStream& io_;
  JobConf conf_;
  std::string key_, value_, split_, pendingKey_;
  int nextCounter_;
  int numReduces_;
  Partitioner* partitioner_ = 0;
  bool havePendingKey_, closed_;
};

static std::string hexDecode(const std::string& hex) {
  std::string out;
  for (size_t i = 0; i + 1 < hex.size(); i += 2) {
    char buf[3] = {hex[i], hex[i + 1], 0};
    out.push_back(static_cast<char>(strtol(buf, NULL, 16)));
  }
  return out;
}

int runTask(const Factory& factory) {
  const char* portEnv = getenv("TPUMR_PIPES_COMMAND_PORT");
  const char* secretEnv = getenv("TPUMR_PIPES_SHARED_SECRET");
  if (!portEnv || !secretEnv) {
    fprintf(stderr, "tpumr-pipes: missing TPUMR_PIPES_COMMAND_PORT / "
                    "TPUMR_PIPES_SHARED_SECRET\n");
    return 2;
  }
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) { perror("socket"); return 2; }
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(atoi(portEnv)));
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (connect(fd, reinterpret_cast<struct sockaddr*>(&addr),
              sizeof(addr)) < 0) {
    perror("connect");
    close(fd);
    return 2;
  }
  int rc = 1;
  try {
    SocketStream io(fd);
    TaskRunner runner(factory, io);
    runner.authenticate(hexDecode(secretEnv));
    rc = runner.run();
  } catch (const std::exception& e) {
    fprintf(stderr, "tpumr-pipes: %s\n", e.what());
    rc = 1;
  }
  close(fd);
  return rc;
}

}  // namespace pipes
}  // namespace tpumr
