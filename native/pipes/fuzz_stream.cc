// fuzz_stream — deterministic fuzz for the pipes child's wire parser
// (SocketStream in tpumr_pipes.cc: LEB128 varints, length-prefixed
// bytes, big-endian doubles — the protocol the child speaks with the
// TaskTracker, ≈ the reference's BinaryProtocol stream).
//
// Includes the runtime TU directly to reach the internal class; built
// with ASAN+UBSAN via `make fuzz` and run by tests/test_native.py.
//
// Phase A: random bytes through a random read schedule — the parser
//          must only ever throw, never crash or over-read.
// Phase B: writer->reader roundtrip property on random values.
//
// argv: [iterations]

#include "tpumr_pipes.cc"  // NOLINT — internal-class test harness

#include <fcntl.h>
#include <cassert>
#include <cstdio>
#include <cstdlib>

using tpumr::pipes::SocketStream;

static uint64_t rng_state;

static uint64_t rnd() {
  uint64_t x = rng_state;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  return rng_state = x;
}

// feed buf through a pipe (capacity-safe: caller keeps n < 60KB)
struct FedStream {
  int fds[2];
  explicit FedStream(const std::string& buf) {
    if (pipe(fds) != 0) abort();
    size_t off = 0;
    while (off < buf.size()) {
      ssize_t w = ::write(fds[1], buf.data() + off, buf.size() - off);
      if (w <= 0) abort();
      off += size_t(w);
    }
    ::close(fds[1]);
  }
  ~FedStream() { ::close(fds[0]); }
};

static void phase_random() {
  std::string buf;
  size_t n = rnd() % 2048;
  for (size_t i = 0; i < n; i++) buf.push_back(char(rnd()));
  FedStream fed(buf);
  SocketStream io(fed.fds[0]);
  try {
    for (;;) {
      switch (rnd() % 3) {
        case 0: io.readVarint(); break;
        case 1: io.readBytes(); break;
        default: io.readDouble(); break;
      }
    }
  } catch (const std::exception&) {
    // expected: closed / too-large / varint-too-long — all fine
  }
}

static int phase_roundtrip() {
  std::vector<uint64_t> ints;
  std::vector<std::string> blobs;
  std::vector<double> dbls;
  std::string buf;
  {
    int tmp[2];
    if (pipe(tmp) != 0) abort();
    SocketStream w(tmp[1]);
    for (int i = 0; i < 8; i++) {
      uint64_t v = rnd() >> (rnd() % 64);
      ints.push_back(v);
      w.writeVarint(v);
      std::string s;
      size_t n = rnd() % 512;
      for (size_t j = 0; j < n; j++) s.push_back(char(rnd()));
      blobs.push_back(s);
      w.writeBytes(s);
      double d;
      uint64_t bits = rnd();
      memcpy(&d, &bits, 8);
      dbls.push_back(d);
      w.writeDouble(d);
    }
    w.flush();
    ::close(tmp[1]);
    char c[4096];
    ssize_t r;
    while ((r = ::read(tmp[0], c, sizeof c)) > 0) buf.append(c, size_t(r));
    ::close(tmp[0]);
  }
  FedStream fed(buf);
  SocketStream io(fed.fds[0]);
  for (int i = 0; i < 8; i++) {
    if (io.readVarint() != ints[size_t(i)]) {
      fprintf(stderr, "FUZZ FAIL: varint roundtrip\n");
      return -1;
    }
    if (io.readBytes() != blobs[size_t(i)]) {
      fprintf(stderr, "FUZZ FAIL: bytes roundtrip\n");
      return -1;
    }
    double d = io.readDouble();
    if (memcmp(&d, &dbls[size_t(i)], 8) != 0) {
      fprintf(stderr, "FUZZ FAIL: double roundtrip\n");
      return -1;
    }
  }
  return 0;
}

int main(int argc, char** argv) {
  long iters = argc > 1 ? atol(argv[1]) : 500;
  for (long it = 0; it < iters; it++) {
    rng_state = 0xF00DF00D ^ uint64_t(it) * 0x9E3779B97F4A7C15ull;
    phase_random();
    if (phase_roundtrip()) return 1;
  }
  printf("fuzz_stream: %ld iterations clean\n", iters);
  return 0;
}
