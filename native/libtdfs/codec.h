/* Typed binary codec — C implementation of tpumr.io.writable.
 *
 * Wire format (tpumr/io/writable.py): 1 tag byte then payload; varints
 * are LEB128 (7-bit groups, high bit = continuation); ints are
 * zigzag-encoded varints; floats are big-endian IEEE float64; ndarray
 * (tag 8) is not supported here (the C client never needs it).
 */
#ifndef TPUMR_CODEC_H
#define TPUMR_CODEC_H

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef enum {
  TD_NULL = 0,
  TD_BYTES = 1,
  TD_TEXT = 2,
  TD_INT = 3,
  TD_FLOAT = 4,
  TD_BOOL = 5,   /* wire tags 5 (true) / 6 (false) */
  TD_LIST = 7,
  TD_DICT = 9
} td_type;

typedef struct td_val {
  td_type t;
  int64_t i;            /* TD_INT / TD_BOOL */
  double f;             /* TD_FLOAT */
  char* s;              /* TD_BYTES / TD_TEXT (owned, NUL-terminated) */
  size_t slen;
  struct td_val* items; /* TD_LIST: n entries; TD_DICT: 2n (k,v,k,v…) */
  size_t n;
} td_val;

/* constructors (deep-own their arguments' copies) */
td_val td_null(void);
td_val td_int(int64_t v);
td_val td_bool(int v);
td_val td_float(double v);
td_val td_text(const char* s);
td_val td_bytes(const char* data, size_t len);
td_val td_list(size_t n);              /* items zeroed; fill items[i] */
td_val td_dict(size_t n_pairs);        /* fill items[2i], items[2i+1] */
void td_free(td_val* v);

/* growable output buffer */
typedef struct {
  char* data;
  size_t len, cap;
} td_buf;

void td_buf_init(td_buf* b);
void td_buf_free(td_buf* b);
void td_encode(td_buf* out, const td_val* v);

/* decode one value from data[*pos..len); returns 0 ok, -1 error */
int td_decode(const char* data, size_t len, size_t* pos, td_val* out);

/* dict lookup by text key; NULL if absent */
const td_val* td_get(const td_val* dict, const char* key);

#ifdef __cplusplus
}
#endif

#endif /* TPUMR_CODEC_H */
