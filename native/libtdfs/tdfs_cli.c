/* tdfs_cli — command-line exerciser for libtdfs (the round-trip tests
 * drive this against a MiniDFSCluster; ≈ the hdfs_test binary shipped
 * with libhdfs). */

#include "tdfs.h"

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

int main(int argc, char** argv) {
  const char* host;
  int port;
  const char* cmd;
  tdfsFS* fs;
  int rc = 2;

  if (argc < 4) {
    fprintf(stderr,
            "usage: tdfs_cli HOST PORT CMD [args]\n"
            "  exists PATH | mkdirs PATH | delete PATH | rename SRC DST\n"
            "  size PATH | cat PATH | put LOCAL PATH\n"
            "  TDFS_SECRET_FILE env: cluster secret for authenticated "
            "clusters\n");
    return 2;
  }
  host = argv[1];
  port = atoi(argv[2]);
  cmd = argv[3];

  fs = tdfs_connect_secure(host, port, getenv("TDFS_SECRET_FILE"));
  if (!fs) {
    fprintf(stderr, "connect failed: %s\n", tdfs_last_error());
    return 2;
  }

  if (strcmp(cmd, "exists") == 0 && argc == 5) {
    rc = tdfs_exists(fs, argv[4]);
    if (rc < 0) {
      fprintf(stderr, "exists failed: %s\n", tdfs_last_error());
      rc = 2;
    } else {
      printf("%s\n", rc == 1 ? "yes" : "no");
      rc = rc == 1 ? 0 : 1;
    }
  } else if (strcmp(cmd, "mkdirs") == 0 && argc == 5) {
    rc = tdfs_mkdirs(fs, argv[4]);
    if (rc < 0) fprintf(stderr, "mkdirs failed: %s\n", tdfs_last_error());
    rc = rc == 1 ? 0 : 1;
  } else if (strcmp(cmd, "delete") == 0 && argc == 5) {
    rc = tdfs_delete(fs, argv[4], 1) == 1 ? 0 : 1;
  } else if (strcmp(cmd, "rename") == 0 && argc == 6) {
    rc = tdfs_rename(fs, argv[4], argv[5]) == 1 ? 0 : 1;
  } else if (strcmp(cmd, "size") == 0 && argc == 5) {
    int64_t n = tdfs_file_size(fs, argv[4]);
    if (n >= 0) {
      printf("%lld\n", (long long)n);
      rc = 0;
    } else {
      fprintf(stderr, "size failed: %s\n", tdfs_last_error());
      rc = 1;
    }
  } else if (strcmp(cmd, "cat") == 0 && argc == 5) {
    int64_t n = 0;
    char* data = tdfs_read_file(fs, argv[4], &n);
    if (data) {
      fwrite(data, 1, (size_t)n, stdout);
      free(data);
      rc = 0;
    } else {
      fprintf(stderr, "read failed: %s\n", tdfs_last_error());
      rc = 1;
    }
  } else if (strcmp(cmd, "put") == 0 && argc == 6) {
    FILE* f = fopen(argv[4], "rb");
    char* data;
    long n;
    if (!f) {
      fprintf(stderr, "cannot open %s\n", argv[4]);
      rc = 1;
    } else {
      fseek(f, 0, SEEK_END);
      n = ftell(f);
      fseek(f, 0, SEEK_SET);
      data = (char*)malloc(n ? (size_t)n : 1);
      if (fread(data, 1, (size_t)n, f) != (size_t)n) n = -1;
      fclose(f);
      if (n < 0 || tdfs_write_file(fs, argv[5], data, n)) {
        fprintf(stderr, "write failed: %s\n", tdfs_last_error());
        rc = 1;
      } else {
        rc = 0;
      }
      free(data);
    }
  } else {
    fprintf(stderr, "unknown command %s\n", cmd);
  }

  tdfs_disconnect(fs);
  return rc;
}
