/* SHA-256 (FIPS 180-4) + HMAC (RFC 2104). See hmac.h. */

#include "hmac.h"

#include <string.h>

static const uint32_t K[64] = {
    0x428a2f98u, 0x71374491u, 0xb5c0fbcfu, 0xe9b5dba5u, 0x3956c25bu,
    0x59f111f1u, 0x923f82a4u, 0xab1c5ed5u, 0xd807aa98u, 0x12835b01u,
    0x243185beu, 0x550c7dc3u, 0x72be5d74u, 0x80deb1feu, 0x9bdc06a7u,
    0xc19bf174u, 0xe49b69c1u, 0xefbe4786u, 0x0fc19dc6u, 0x240ca1ccu,
    0x2de92c6fu, 0x4a7484aau, 0x5cb0a9dcu, 0x76f988dau, 0x983e5152u,
    0xa831c66du, 0xb00327c8u, 0xbf597fc7u, 0xc6e00bf3u, 0xd5a79147u,
    0x06ca6351u, 0x14292967u, 0x27b70a85u, 0x2e1b2138u, 0x4d2c6dfcu,
    0x53380d13u, 0x650a7354u, 0x766a0abbu, 0x81c2c92eu, 0x92722c85u,
    0xa2bfe8a1u, 0xa81a664bu, 0xc24b8b70u, 0xc76c51a3u, 0xd192e819u,
    0xd6990624u, 0xf40e3585u, 0x106aa070u, 0x19a4c116u, 0x1e376c08u,
    0x2748774cu, 0x34b0bcb5u, 0x391c0cb3u, 0x4ed8aa4au, 0x5b9cca4fu,
    0x682e6ff3u, 0x748f82eeu, 0x78a5636fu, 0x84c87814u, 0x8cc70208u,
    0x90befffau, 0xa4506cebu, 0xbef9a3f7u, 0xc67178f2u};

#define ROTR(x, n) (((x) >> (n)) | ((x) << (32 - (n))))

static void sha256_block(td_sha256_ctx* c, const unsigned char* p) {
  uint32_t w[64], a, b, d, e, f, g, h, s0, s1, t1, t2, cc;
  int i;
  for (i = 0; i < 16; i++)
    w[i] = ((uint32_t)p[4 * i] << 24) | ((uint32_t)p[4 * i + 1] << 16) |
           ((uint32_t)p[4 * i + 2] << 8) | p[4 * i + 3];
  for (i = 16; i < 64; i++) {
    s0 = ROTR(w[i - 15], 7) ^ ROTR(w[i - 15], 18) ^ (w[i - 15] >> 3);
    s1 = ROTR(w[i - 2], 17) ^ ROTR(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  a = c->h[0]; b = c->h[1]; cc = c->h[2]; d = c->h[3];
  e = c->h[4]; f = c->h[5]; g = c->h[6]; h = c->h[7];
  for (i = 0; i < 64; i++) {
    s1 = ROTR(e, 6) ^ ROTR(e, 11) ^ ROTR(e, 25);
    t1 = h + s1 + ((e & f) ^ (~e & g)) + K[i] + w[i];
    s0 = ROTR(a, 2) ^ ROTR(a, 13) ^ ROTR(a, 22);
    t2 = s0 + ((a & b) ^ (a & cc) ^ (b & cc));
    h = g; g = f; f = e; e = d + t1;
    d = cc; cc = b; b = a; a = t1 + t2;
  }
  c->h[0] += a; c->h[1] += b; c->h[2] += cc; c->h[3] += d;
  c->h[4] += e; c->h[5] += f; c->h[6] += g; c->h[7] += h;
}

void td_sha256_init(td_sha256_ctx* c) {
  c->h[0] = 0x6a09e667u; c->h[1] = 0xbb67ae85u;
  c->h[2] = 0x3c6ef372u; c->h[3] = 0xa54ff53au;
  c->h[4] = 0x510e527fu; c->h[5] = 0x9b05688cu;
  c->h[6] = 0x1f83d9abu; c->h[7] = 0x5be0cd19u;
  c->len = 0;
  c->buflen = 0;
}

void td_sha256_update(td_sha256_ctx* c, const void* data, size_t n) {
  const unsigned char* p = (const unsigned char*)data;
  c->len += n;
  while (n) {
    size_t take = 64 - c->buflen;
    if (take > n) take = n;
    memcpy(c->buf + c->buflen, p, take);
    c->buflen += take;
    p += take;
    n -= take;
    if (c->buflen == 64) {
      sha256_block(c, c->buf);
      c->buflen = 0;
    }
  }
}

void td_sha256_final(td_sha256_ctx* c, unsigned char out[32]) {
  uint64_t bits = c->len * 8;
  unsigned char pad = 0x80;
  unsigned char lenbe[8];
  int i;
  /* `bits` captured above — padding pushed through update() after this
   * point no longer affects the encoded message length */
  td_sha256_update(c, &pad, 1);
  while (c->buflen != 56) {
    unsigned char z = 0;
    td_sha256_update(c, &z, 1);
  }
  for (i = 0; i < 8; i++) lenbe[i] = (unsigned char)(bits >> (56 - 8 * i));
  memcpy(c->buf + c->buflen, lenbe, 8);
  sha256_block(c, c->buf);
  for (i = 0; i < 8; i++) {
    out[4 * i] = (unsigned char)(c->h[i] >> 24);
    out[4 * i + 1] = (unsigned char)(c->h[i] >> 16);
    out[4 * i + 2] = (unsigned char)(c->h[i] >> 8);
    out[4 * i + 3] = (unsigned char)c->h[i];
  }
}

static void sha256_once(const void* d1, size_t n1, const void* d2, size_t n2,
                        unsigned char out[32]) {
  td_sha256_ctx c;
  td_sha256_init(&c);
  td_sha256_update(&c, d1, n1);
  if (d2) td_sha256_update(&c, d2, n2);
  td_sha256_final(&c, out);
}

void td_hmac_sha256_hex(const void* key, size_t keylen,
                        const void* msg, size_t msglen,
                        char out_hex[65]) {
  unsigned char k[64], ipad[64], opad[64], inner[32], mac[32];
  static const char hexd[] = "0123456789abcdef";
  td_sha256_ctx c;
  int i;
  memset(k, 0, sizeof k);
  if (keylen > 64) {
    unsigned char kh[32];
    sha256_once(key, keylen, NULL, 0, kh);
    memcpy(k, kh, 32);
  } else {
    memcpy(k, key, keylen);
  }
  for (i = 0; i < 64; i++) {
    ipad[i] = (unsigned char)(k[i] ^ 0x36);
    opad[i] = (unsigned char)(k[i] ^ 0x5c);
  }
  td_sha256_init(&c);
  td_sha256_update(&c, ipad, 64);
  td_sha256_update(&c, msg, msglen);
  td_sha256_final(&c, inner);
  td_sha256_init(&c);
  td_sha256_update(&c, opad, 64);
  td_sha256_update(&c, inner, 32);
  td_sha256_final(&c, mac);
  for (i = 0; i < 32; i++) {
    out_hex[2 * i] = hexd[mac[i] >> 4];
    out_hex[2 * i + 1] = hexd[mac[i] & 15];
  }
  out_hex[64] = 0;
}
