/* fuzz_codec — deterministic fuzz loop for the td codec (codec.c), the
 * frame payload parser libtdfs feeds with bytes read off the wire.
 *
 * Built with ASAN+UBSAN (make fuzz) and run in CI (tests/test_native.py
 * TestSanitizers): libFuzzer isn't in this toolchain, so this is a
 * self-contained driver — xorshift PRNG, fixed seeds, three phases:
 *
 *   A  random buffers -> td_decode must never crash/leak, only return -1
 *   B  valid encodings mutated/truncated -> same
 *   C  roundtrip property: encode(decode(encode(v))) is byte-identical
 *
 * argv: [iterations] [corpus-dir] — corpus files are decoded as-is and
 * under mutation. SURVEY.md §5 sanitizer note; reference analog: the
 * fault-injection tests around Writable deserialization.
 */

#include "codec.h"

#include <dirent.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

static uint64_t rng_state = 0x9E3779B97F4A7C15ull;

static uint64_t rnd(void) {
  uint64_t x = rng_state;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  return rng_state = x;
}

static void decode_all(const char* data, size_t len) {
  size_t pos = 0;
  while (pos < len) {
    td_val v;
    if (td_decode(data, len, &pos, &v)) break;
    td_free(&v);
  }
}

/* a random valid value, bounded depth/size */
static td_val gen_val(int depth) {
  switch (rnd() % (depth > 3 ? 6 : 8)) {
    case 0: return td_null();
    case 1: return td_int((int64_t)rnd());
    case 2: return td_bool(rnd() & 1);
    case 3: {
      double d;
      uint64_t bits = rnd();
      memcpy(&d, &bits, 8);
      return td_float(d);
    }
    case 4: {
      char buf[64];
      size_t n = rnd() % sizeof buf, i;
      for (i = 0; i < n; i++) buf[i] = (char)rnd();
      return td_bytes(buf, n);
    }
    case 5: {
      char buf[32];
      size_t n = rnd() % (sizeof buf - 1), i;
      for (i = 0; i < n; i++) buf[i] = (char)('a' + rnd() % 26);
      buf[n] = 0;
      return td_text(buf);
    }
    case 6: {
      size_t n = rnd() % 5, i;
      td_val v = td_list(n);
      for (i = 0; i < n; i++) v.items[i] = gen_val(depth + 1);
      return v;
    }
    default: {
      size_t n = rnd() % 4, i;
      td_val v = td_dict(n);
      for (i = 0; i < n; i++) {
        char key[16];
        snprintf(key, sizeof key, "k%llu",
                 (unsigned long long)(rnd() % 100));
        v.items[2 * i] = td_text(key);
        v.items[2 * i + 1] = gen_val(depth + 1);
      }
      return v;
    }
  }
}

static int roundtrip(const td_val* v) {
  td_buf b1, b2;
  td_val back;
  size_t pos = 0;
  int ok;
  td_buf_init(&b1);
  td_buf_init(&b2);
  td_encode(&b1, v);
  if (td_decode(b1.data, b1.len, &pos, &back)) {
    fprintf(stderr, "FUZZ FAIL: valid encoding did not decode\n");
    td_buf_free(&b1);
    td_buf_free(&b2);
    return -1;
  }
  td_encode(&b2, &back);
  ok = b1.len == b2.len && memcmp(b1.data, b2.data, b1.len) == 0;
  if (!ok)
    fprintf(stderr, "FUZZ FAIL: roundtrip not byte-identical "
            "(%zu vs %zu bytes)\n", b1.len, b2.len);
  td_free(&back);
  td_buf_free(&b1);
  td_buf_free(&b2);
  return ok ? 0 : -1;
}

static void mutate_and_decode(const char* data, size_t len) {
  char* m = (char*)malloc(len ? len : 1);
  size_t cut = len ? 1 + rnd() % len : 0, flips = 1 + rnd() % 8, i;
  memcpy(m, data, len);
  for (i = 0; i < flips && len; i++)
    m[rnd() % len] = (char)rnd();
  decode_all(m, len);
  decode_all(m, cut);          /* truncation */
  free(m);
}

static void fuzz_corpus_file(const char* path) {
  FILE* f = fopen(path, "rb");
  char* data;
  long sz;
  int i;
  if (!f) return;
  fseek(f, 0, SEEK_END);
  sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (sz < 0 || sz > 1 << 20) {
    fclose(f);
    return;
  }
  data = (char*)malloc(sz ? (size_t)sz : 1);
  if (fread(data, 1, (size_t)sz, f) != (size_t)sz) sz = 0;
  fclose(f);
  decode_all(data, (size_t)sz);
  for (i = 0; i < 50; i++) mutate_and_decode(data, (size_t)sz);
  free(data);
}

int main(int argc, char** argv) {
  long iters = argc > 1 ? atol(argv[1]) : 2000;
  long it;
  for (it = 0; it < iters; it++) {
    rng_state = 0x9E3779B97F4A7C15ull + (uint64_t)it * 2654435761u;
    /* A: random garbage */
    {
      char buf[512];
      size_t n = rnd() % sizeof buf, i;
      for (i = 0; i < n; i++) buf[i] = (char)rnd();
      decode_all(buf, n);
    }
    /* B+C: valid value -> roundtrip property -> mutations */
    {
      td_val v = gen_val(0);
      td_buf b;
      if (roundtrip(&v)) return 1;
      td_buf_init(&b);
      td_encode(&b, &v);
      mutate_and_decode(b.data, b.len);
      td_buf_free(&b);
      td_free(&v);
    }
  }
  if (argc > 2) {
    DIR* d = opendir(argv[2]);
    struct dirent* e;
    if (d) {
      while ((e = readdir(d)) != NULL) {
        char path[4096];
        if (e->d_name[0] == '.') continue;
        snprintf(path, sizeof path, "%s/%s", argv[2], e->d_name);
        fuzz_corpus_file(path);
      }
      closedir(d);
    }
  }
  printf("fuzz_codec: %ld iterations clean\n", iters);
  return 0;
}
