/* libtdfs — see tdfs.h. RPC framing: 4-byte big-endian length +
 * codec-serialized dict {"id","method","params"} (tpumr/ipc/rpc.py).
 * Responses: {"id","result"} or {"id","error","traceback"}.
 *
 * Cluster auth (tpumr.rpc.secret): an authenticated server greets each
 * connection with {"hello":1,"nonce":...}; every request then carries
 * cid/user/ts plus auth = HMAC-SHA256(secret, canon) where canon is the
 * codec-serialized list [cid, id, method, params, ts, port, nonce,
 * user, scope] (tpumr/ipc/rpc.py:_sign). Use tdfs_connect_secure. */

#include "tdfs.h"
#include "codec.h"
#include "hmac.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <pwd.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <time.h>
#include <unistd.h>

/* Sanity cap on a server frame length read off the wire. Must exceed
 * any configured dfs.block.size (read_block returns a whole block as
 * one TD_BYTES frame); 1 GiB covers every sane block size while still
 * refusing a hostile server's 4 GiB allocation bomb. */
#define TDFS_MAX_FRAME (1024u * 1024 * 1024)

static __thread char g_err[1024];

const char* tdfs_last_error(void) { return g_err; }

static void set_err(const char* fmt, const char* detail) {
  snprintf(g_err, sizeof g_err, fmt, detail ? detail : "");
}

/* ------------------------------------------------------------ rpc core */

typedef struct {
  int fd;
  int64_t next_id;
  int port;                 /* dialed port — part of the signature canon */
  char secret[256];
  size_t secret_len;        /* 0 = auth off */
  char nonce[128];          /* server hello nonce (hex text) */
  char cid[33];             /* per-connection client id (hex) */
  char user[64];            /* asserted simple-auth identity */
} rpc_conn;

static void fill_identity(rpc_conn* c) {
  /* getpwuid_r, not getpwuid: concurrent connects (one tdfsFS per
   * thread — the documented contract) must not race on libc's shared
   * passwd buffer (found by the TSAN stress tier) */
  struct passwd pwbuf, *pw = NULL;
  char pwstr[1024];
  if (getpwuid_r(getuid(), &pwbuf, pwstr, sizeof pwstr, &pw) != 0)
    pw = NULL;
  const char* u = pw ? pw->pw_name : getenv("USER");
  unsigned char rnd[16];
  size_t i;
  FILE* f = fopen("/dev/urandom", "rb");
  if (!f || fread(rnd, 1, sizeof rnd, f) != sizeof rnd) {
    /* the counter keeps same-second reconnects (which often get the
     * same rpc_conn address back from malloc) from repeating a cid */
    static _Atomic unsigned g_cid_counter;
    unsigned seed = (unsigned)(getpid() ^ (uintptr_t)c ^
                               (unsigned)time(NULL) ^
                               (++g_cid_counter << 16));
    for (i = 0; i < sizeof rnd; i++)
      rnd[i] = (unsigned char)rand_r(&seed);
  }
  if (f) fclose(f);
  for (i = 0; i < sizeof rnd; i++)
    snprintf(c->cid + 2 * i, 3, "%02x", rnd[i]);
  snprintf(c->user, sizeof c->user, "%s", u ? u : "nobody");
}

static int read_all(int fd, char* p, size_t n);

/* Read one frame into a freshly decoded td_val; returns 0 ok. */
static int recv_frame(int fd, td_val* out) {
  unsigned char lenbe[4];
  uint32_t rlen;
  char* rdata;
  size_t pos = 0;
  if (read_all(fd, (char*)lenbe, 4)) return -1;
  rlen = ((uint32_t)lenbe[0] << 24) | ((uint32_t)lenbe[1] << 16) |
         ((uint32_t)lenbe[2] << 8) | lenbe[3];
  /* The length word comes off the wire: bound it (server frames are
     block-chunk sized, far below this) and never trust malloc. */
  if (rlen > TDFS_MAX_FRAME) {
    set_err("oversized frame from server (%s)", "len > 1 GiB");
    return -1;
  }
  rdata = (char*)malloc(rlen ? rlen : 1);
  if (!rdata) {
    set_err("out of memory for %s", "rpc frame");
    return -1;
  }
  if (read_all(fd, rdata, rlen)) {
    free(rdata);
    return -1;
  }
  if (td_decode(rdata, rlen, &pos, out)) {
    free(rdata);
    return -1;
  }
  free(rdata);
  return 0;
}

static int rpc_open(rpc_conn* c, const char* host, int port) {
  struct addrinfo hints, *res = NULL, *rp;
  char portbuf[16];
  memset(&hints, 0, sizeof hints);
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  snprintf(portbuf, sizeof portbuf, "%d", port);
  if (getaddrinfo(host, portbuf, &hints, &res)) {
    set_err("cannot resolve %s", host);
    return -1;
  }
  c->fd = -1;
  for (rp = res; rp; rp = rp->ai_next) {
    c->fd = socket(rp->ai_family, rp->ai_socktype, rp->ai_protocol);
    if (c->fd < 0) continue;
    if (connect(c->fd, rp->ai_addr, rp->ai_addrlen) == 0) break;
    close(c->fd);
    c->fd = -1;
  }
  freeaddrinfo(res);
  if (c->fd < 0) {
    set_err("cannot connect to %s", host);
    return -1;
  }
  c->next_id = 1;
  c->port = port;
  fill_identity(c);
  if (c->secret_len) {
    /* authenticated servers greet with a per-connection nonce the
     * client must fold into every signature. Bounded wait (5s, like
     * the Python client's fail-fast, rpc.py:364-373): an OPEN server
     * sends nothing until a request arrives — without the timeout a
     * config skew would hang forever instead of diagnosing. */
    td_val hello;
    const td_val* nv;
    struct timeval hello_to = {5, 0}, clear_to = {0, 0};
    setsockopt(c->fd, SOL_SOCKET, SO_RCVTIMEO, &hello_to,
               sizeof hello_to);
    if (recv_frame(c->fd, &hello)) {
      close(c->fd);
      set_err("no auth hello from %s — secret configured but server "
              "appears unauthenticated?", host);
      return -1;
    }
    setsockopt(c->fd, SOL_SOCKET, SO_RCVTIMEO, &clear_to,
               sizeof clear_to);
    nv = td_get(&hello, "nonce");
    if (!nv || nv->t != TD_TEXT) {
      td_free(&hello);
      close(c->fd);
      set_err("malformed auth hello from %s", host);
      return -1;
    }
    snprintf(c->nonce, sizeof c->nonce, "%s", nv->s);
    td_free(&hello);
  }
  return 0;
}

static int write_all(int fd, const char* p, size_t n) {
  while (n) {
    ssize_t w = write(fd, p, n);
    if (w <= 0) return -1;
    p += w;
    n -= (size_t)w;
  }
  return 0;
}

static int read_all(int fd, char* p, size_t n) {
  while (n) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) return -1;
    p += r;
    n -= (size_t)r;
  }
  return 0;
}

/* Calls method(params); params ownership transfers (freed here).
 * On success returns 0 and fills *result (caller td_free's). */
static int rpc_call(rpc_conn* c, const char* method, td_val params,
                    td_val* result) {
  int authed = c->secret_len > 0;
  int64_t id = c->next_id++;
  char auth_hex[65];
  double ts = 0;
  td_val req;
  td_buf buf;
  unsigned char lenbe[4];
  td_val resp;
  const td_val* err;
  const td_val* res;
  size_t k = 0;
  int rc = -1;

  *result = td_null();  /* every failure path leaves a freeable value */

  if (authed) {
    /* canon = [cid, id, method, params, ts, port, nonce, user, scope]
     * (tpumr/ipc/rpc.py:_sign) — params is BORROWED into the canon
     * list and blanked before the free so ownership stays with req */
    struct timeval tv;
    td_val canon;
    td_buf cbuf;
    gettimeofday(&tv, NULL);
    ts = (double)tv.tv_sec + (double)tv.tv_usec / 1e6;
    canon = td_list(9);
    canon.items[0] = td_text(c->cid);
    canon.items[1] = td_int(id);
    canon.items[2] = td_text(method);
    canon.items[3] = params;                 /* borrowed */
    canon.items[4] = td_float(ts);
    canon.items[5] = td_int(c->port);
    canon.items[6] = td_text(c->nonce);
    canon.items[7] = td_text(c->user);
    canon.items[8] = td_null();              /* scope: cluster secret */
    td_buf_init(&cbuf);
    td_encode(&cbuf, &canon);
    memset(&canon.items[3], 0, sizeof(td_val));  /* un-borrow params */
    canon.items[3].t = TD_NULL;
    td_free(&canon);
    td_hmac_sha256_hex(c->secret, c->secret_len, cbuf.data, cbuf.len,
                       auth_hex);
    td_buf_free(&cbuf);
  }

  req = td_dict(authed ? 7 : 5);
  req.items[k++] = td_text("id");
  req.items[k++] = td_int(id);
  req.items[k++] = td_text("cid");
  req.items[k++] = td_text(c->cid);
  req.items[k++] = td_text("method");
  req.items[k++] = td_text(method);
  req.items[k++] = td_text("user");
  req.items[k++] = td_text(c->user);
  req.items[k++] = td_text("params");
  req.items[k++] = params;
  if (authed) {
    req.items[k++] = td_text("ts");
    req.items[k++] = td_float(ts);
    req.items[k++] = td_text("auth");
    req.items[k++] = td_text(auth_hex);
  }

  td_buf_init(&buf);
  td_encode(&buf, &req);
  td_free(&req);

  lenbe[0] = (unsigned char)(buf.len >> 24);
  lenbe[1] = (unsigned char)(buf.len >> 16);
  lenbe[2] = (unsigned char)(buf.len >> 8);
  lenbe[3] = (unsigned char)buf.len;
  if (write_all(c->fd, (const char*)lenbe, 4) ||
      write_all(c->fd, buf.data, buf.len)) {
    td_buf_free(&buf);
    set_err("rpc send failed%s", NULL);
    return -1;
  }
  td_buf_free(&buf);

  if (recv_frame(c->fd, &resp)) {
    set_err("rpc recv failed%s", NULL);
    return -1;
  }
  /* an unauth client talking to an authed server sees the hello frame
   * first — skip it so the real (auth error) response surfaces */
  while (td_get(&resp, "hello")) {
    td_free(&resp);
    if (recv_frame(c->fd, &resp)) {
      set_err("rpc recv failed%s", NULL);
      return -1;
    }
  }

  err = td_get(&resp, "error");
  if (err && err->t == TD_TEXT) {
    set_err("remote error: %s", err->s);
  } else {
    res = td_get(&resp, "result");
    if (res) {
      /* steal the result subtree: blank it in resp so td_free skips it */
      *result = *res;
      memset((void*)res, 0, sizeof(td_val));
    } else {
      *result = td_null();
    }
    rc = 0;
  }
  td_free(&resp);
  return rc;
}

/* ------------------------------------------------------------ fs handle */

struct tdfsFS_s {
  rpc_conn nn;
  char client_name[64];
};

/* Open a DataNode connection inheriting the cluster secret: stack
 * rpc_conn structs MUST be zeroed (rpc_open assumes secret fields are
 * meaningful) and signed exactly like the NameNode channel — each
 * connection gets its own hello nonce from its own server. */
static int dn_open(tdfsFS* fs, rpc_conn* dn, const char* host, int port) {
  memset(dn, 0, sizeof *dn);
  memcpy(dn->secret, fs->nn.secret, fs->nn.secret_len);
  dn->secret_len = fs->nn.secret_len;
  return rpc_open(dn, host, port);
}

tdfsFS* tdfs_connect(const char* host, int port) {
  return tdfs_connect_secure(host, port, NULL);
}

tdfsFS* tdfs_connect_secure(const char* host, int port,
                            const char* secret_file) {
  tdfsFS* fs = (tdfsFS*)calloc(1, sizeof(tdfsFS));
  if (secret_file && *secret_file) {
    /* same semantics as tpumr.rpc.secret.file: bytes, whitespace
     * stripped at both ends */
    FILE* f = fopen(secret_file, "rb");
    size_t n, start, end;
    if (!f) {
      set_err("cannot open secret file %s", secret_file);
      free(fs);
      return NULL;
    }
    n = fread(fs->nn.secret, 1, sizeof fs->nn.secret - 1, f);
    if (n == sizeof fs->nn.secret - 1 && fgetc(f) != EOF) {
      /* never sign with a silently-truncated key: Python reads the
       * whole file, so a truncated HMAC would fail with a misleading
       * "not signed" — fail loudly here instead */
      fclose(f);
      set_err("secret file %s exceeds the supported 255 bytes",
              secret_file);
      free(fs);
      return NULL;
    }
    fclose(f);
    start = 0;
    end = n;
    while (end > start && (unsigned char)fs->nn.secret[end - 1] <= ' ')
      end--;
    while (start < end && (unsigned char)fs->nn.secret[start] <= ' ')
      start++;
    memmove(fs->nn.secret, fs->nn.secret + start, end - start);
    fs->nn.secret_len = end - start;
    if (!fs->nn.secret_len) {
      set_err("secret file %s is empty", secret_file);
      free(fs);
      return NULL;
    }
  }
  if (rpc_open(&fs->nn, host, port)) {
    free(fs);
    return NULL;
  }
  snprintf(fs->client_name, sizeof fs->client_name, "libtdfs-%d",
           (int)getpid());
  return fs;
}

void tdfs_disconnect(tdfsFS* fs) {
  if (!fs) return;
  close(fs->nn.fd);
  free(fs);
}

/* one-arg / two-arg boolean helpers */

static int call_bool(tdfsFS* fs, const char* method, td_val params) {
  td_val result;
  int rc;
  if (rpc_call(&fs->nn, method, params, &result)) return -1;
  rc = (result.t == TD_BOOL || result.t == TD_INT) ? (result.i ? 1 : 0) : 0;
  td_free(&result);
  return rc;
}

int tdfs_exists(tdfsFS* fs, const char* path) {
  td_val p = td_list(1);
  p.items[0] = td_text(path);
  return call_bool(fs, "exists", p);
}

int tdfs_mkdirs(tdfsFS* fs, const char* path) {
  td_val p = td_list(1);
  p.items[0] = td_text(path);
  return call_bool(fs, "mkdirs", p);
}

int tdfs_delete(tdfsFS* fs, const char* path, int recursive) {
  td_val p = td_list(2);
  p.items[0] = td_text(path);
  p.items[1] = td_bool(recursive);
  return call_bool(fs, "delete", p);
}

int tdfs_rename(tdfsFS* fs, const char* src, const char* dst) {
  td_val p = td_list(2);
  p.items[0] = td_text(src);
  p.items[1] = td_text(dst);
  return call_bool(fs, "rename", p);
}

int64_t tdfs_file_size(tdfsFS* fs, const char* path) {
  td_val p = td_list(1);
  td_val st;
  const td_val* len;
  int64_t out = -1;
  p.items[0] = td_text(path);
  if (rpc_call(&fs->nn, "get_status", p, &st)) return -1;
  len = td_get(&st, "length");
  if (len && len->t == TD_INT) out = len->i;
  td_free(&st);
  return out;
}

/* ------------------------------------------------------------ read */

static int dn_split(const char* addr, char* host, size_t hostsz, int* port) {
  const char* colon = strrchr(addr, ':');
  size_t hl;
  if (!colon) return -1;
  hl = (size_t)(colon - addr);
  if (hl + 1 > hostsz) return -1;
  memcpy(host, addr, hl);
  host[hl] = 0;
  *port = atoi(colon + 1);
  return 0;
}

char* tdfs_read_file(tdfsFS* fs, const char* path, int64_t* len_out) {
  td_val p = td_list(1);
  td_val blocks;
  char* out = NULL;
  size_t total = 0, off = 0, i, j;

  p.items[0] = td_text(path);
  if (rpc_call(&fs->nn, "get_block_locations", p, &blocks)) return NULL;
  if (blocks.t != TD_LIST) {
    td_free(&blocks);
    set_err("unexpected block list%s", NULL);
    return NULL;
  }
  for (i = 0; i < blocks.n; i++) {
    const td_val* sz = td_get(&blocks.items[i], "size");
    total += sz && sz->t == TD_INT ? (size_t)sz->i : 0;
  }
  out = (char*)malloc(total ? total : 1);

  for (i = 0; i < blocks.n; i++) {
    const td_val* bid = td_get(&blocks.items[i], "block_id");
    const td_val* locs = td_get(&blocks.items[i], "locations");
    int ok = 0;
    if (!bid || !locs || locs->t != TD_LIST) {
      free(out);
      td_free(&blocks);
      set_err("malformed block entry for %s", path);
      return NULL;
    }
    for (j = 0; j < locs->n && !ok; j++) {  /* replica failover */
      char host[256];
      int port;
      rpc_conn dn;
      td_val dp;
      td_val data = td_null();
      if (locs->items[j].t != TD_TEXT ||
          dn_split(locs->items[j].s, host, sizeof host, &port))
        continue;
      if (dn_open(fs, &dn, host, port)) continue;
      dp = td_list(1);
      dp.items[0] = td_int(bid->i);
      if (rpc_call(&dn, "read_block", dp, &data) == 0 &&
          data.t == TD_BYTES) {
        if (off + data.slen > total) {
          /* replica longer than NameNode metadata: corrupt/byzantine */
          td_free(&data);
          close(dn.fd);
          free(out);
          td_free(&blocks);
          set_err("replica larger than metadata for %s", path);
          return NULL;
        }
        memcpy(out + off, data.s, data.slen);
        off += data.slen;
        ok = 1;
      }
      td_free(&data);
      close(dn.fd);
    }
    if (!ok) {
      free(out);
      td_free(&blocks);
      set_err("no replica readable for a block of %s", path);
      return NULL;
    }
  }
  td_free(&blocks);
  *len_out = (int64_t)off;
  return out;
}

/* ------------------------------------------------------------ write */

int tdfs_write_file(tdfsFS* fs, const char* path, const char* data,
                    int64_t len) {
  td_val p = td_list(5);
  td_val meta;
  const td_val* bs;
  int64_t block_size, off = 0, prev = -1, last = -1;

  p.items[0] = td_text(path);
  p.items[1] = td_text(fs->client_name);
  p.items[2] = td_null();  /* replication: default */
  p.items[3] = td_null();  /* block size: default */
  p.items[4] = td_bool(1); /* overwrite */
  if (rpc_call(&fs->nn, "create", p, &meta)) return -1;
  bs = td_get(&meta, "block_size");
  block_size = bs && bs->t == TD_INT ? bs->i : (8 << 20);
  td_free(&meta);

  while (off < len || (len == 0 && off == 0)) {
    int64_t n = len - off < block_size ? len - off : block_size;
    td_val ap, alloc, wp, wres;
    const td_val* bid;
    const td_val* targets;
    char host[256];
    int port;
    rpc_conn dn;
    size_t k;

    if (len == 0) break; /* empty file: create+complete only */

    ap = td_list(4);
    ap.items[0] = td_text(path);
    ap.items[1] = td_text(fs->client_name);
    ap.items[2] = td_int(prev);
    ap.items[3] = td_list(0); /* excluded */
    if (rpc_call(&fs->nn, "add_block", ap, &alloc)) return -1;
    bid = td_get(&alloc, "block_id");
    targets = td_get(&alloc, "targets");
    if (!bid || !targets || targets->t != TD_LIST || targets->n == 0 ||
        targets->items[0].t != TD_TEXT ||
        dn_split(targets->items[0].s, host, sizeof host, &port)) {
      td_free(&alloc);
      set_err("bad block allocation for %s", path);
      return -1;
    }
    if (dn_open(fs, &dn, host, port)) {
      td_free(&alloc);
      return -1;
    }
    wp = td_list(3);
    wp.items[0] = td_int(bid->i);
    wp.items[1] = td_bytes(data + off, (size_t)n);
    wp.items[2] = td_list(targets->n - 1); /* downstream pipeline */
    for (k = 1; k < targets->n; k++)
      wp.items[2].items[k - 1] = td_text(targets->items[k].s);
    td_free(&alloc);
    if (rpc_call(&dn, "write_block", wp, &wres)) {
      close(dn.fd);
      return -1;
    }
    td_free(&wres);
    close(dn.fd);
    prev = n;
    last = n;
    off += n;
  }

  {
    td_val cp = td_list(3);
    td_val cres;
    cp.items[0] = td_text(path);
    cp.items[1] = td_text(fs->client_name);
    cp.items[2] = td_int(last);
    if (rpc_call(&fs->nn, "complete", cp, &cres)) return -1;
    td_free(&cres);
  }
  return 0;
}
