/* libtdfs — see tdfs.h. RPC framing: 4-byte big-endian length +
 * codec-serialized dict {"id","method","params"} (tpumr/ipc/rpc.py).
 * Responses: {"id","result"} or {"id","error","traceback"}. */

#include "tdfs.h"
#include "codec.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

static __thread char g_err[1024];

const char* tdfs_last_error(void) { return g_err; }

static void set_err(const char* fmt, const char* detail) {
  snprintf(g_err, sizeof g_err, fmt, detail ? detail : "");
}

/* ------------------------------------------------------------ rpc core */

typedef struct {
  int fd;
  int64_t next_id;
} rpc_conn;

static int rpc_open(rpc_conn* c, const char* host, int port) {
  struct addrinfo hints, *res = NULL, *rp;
  char portbuf[16];
  memset(&hints, 0, sizeof hints);
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  snprintf(portbuf, sizeof portbuf, "%d", port);
  if (getaddrinfo(host, portbuf, &hints, &res)) {
    set_err("cannot resolve %s", host);
    return -1;
  }
  c->fd = -1;
  for (rp = res; rp; rp = rp->ai_next) {
    c->fd = socket(rp->ai_family, rp->ai_socktype, rp->ai_protocol);
    if (c->fd < 0) continue;
    if (connect(c->fd, rp->ai_addr, rp->ai_addrlen) == 0) break;
    close(c->fd);
    c->fd = -1;
  }
  freeaddrinfo(res);
  if (c->fd < 0) {
    set_err("cannot connect to %s", host);
    return -1;
  }
  c->next_id = 1;
  return 0;
}

static int write_all(int fd, const char* p, size_t n) {
  while (n) {
    ssize_t w = write(fd, p, n);
    if (w <= 0) return -1;
    p += w;
    n -= (size_t)w;
  }
  return 0;
}

static int read_all(int fd, char* p, size_t n) {
  while (n) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) return -1;
    p += r;
    n -= (size_t)r;
  }
  return 0;
}

/* Calls method(params); params ownership transfers (freed here).
 * On success returns 0 and fills *result (caller td_free's). */
static int rpc_call(rpc_conn* c, const char* method, td_val params,
                    td_val* result) {
  td_val req = td_dict(3);
  td_buf buf;
  unsigned char lenbe[4];
  uint32_t rlen;
  char* rdata;
  size_t pos = 0;
  td_val resp;
  const td_val* err;
  const td_val* res;
  int rc = -1;

  *result = td_null();  /* every failure path leaves a freeable value */

  req.items[0] = td_text("id");
  req.items[1] = td_int(c->next_id++);
  req.items[2] = td_text("method");
  req.items[3] = td_text(method);
  req.items[4] = td_text("params");
  req.items[5] = params;

  td_buf_init(&buf);
  td_encode(&buf, &req);
  td_free(&req);

  lenbe[0] = (unsigned char)(buf.len >> 24);
  lenbe[1] = (unsigned char)(buf.len >> 16);
  lenbe[2] = (unsigned char)(buf.len >> 8);
  lenbe[3] = (unsigned char)buf.len;
  if (write_all(c->fd, (const char*)lenbe, 4) ||
      write_all(c->fd, buf.data, buf.len)) {
    td_buf_free(&buf);
    set_err("rpc send failed%s", NULL);
    return -1;
  }
  td_buf_free(&buf);

  if (read_all(c->fd, (char*)lenbe, 4)) {
    set_err("rpc recv failed%s", NULL);
    return -1;
  }
  rlen = ((uint32_t)lenbe[0] << 24) | ((uint32_t)lenbe[1] << 16) |
         ((uint32_t)lenbe[2] << 8) | lenbe[3];
  rdata = (char*)malloc(rlen);
  if (read_all(c->fd, rdata, rlen)) {
    free(rdata);
    set_err("rpc recv failed%s", NULL);
    return -1;
  }
  if (td_decode(rdata, rlen, &pos, &resp)) {
    free(rdata);
    set_err("rpc decode failed%s", NULL);
    return -1;
  }
  free(rdata);

  err = td_get(&resp, "error");
  if (err && err->t == TD_TEXT) {
    set_err("remote error: %s", err->s);
  } else {
    res = td_get(&resp, "result");
    if (res) {
      /* steal the result subtree: blank it in resp so td_free skips it */
      *result = *res;
      memset((void*)res, 0, sizeof(td_val));
    } else {
      *result = td_null();
    }
    rc = 0;
  }
  td_free(&resp);
  return rc;
}

/* ------------------------------------------------------------ fs handle */

struct tdfsFS_s {
  rpc_conn nn;
  char client_name[64];
};

tdfsFS* tdfs_connect(const char* host, int port) {
  tdfsFS* fs = (tdfsFS*)calloc(1, sizeof(tdfsFS));
  if (rpc_open(&fs->nn, host, port)) {
    free(fs);
    return NULL;
  }
  snprintf(fs->client_name, sizeof fs->client_name, "libtdfs-%d",
           (int)getpid());
  return fs;
}

void tdfs_disconnect(tdfsFS* fs) {
  if (!fs) return;
  close(fs->nn.fd);
  free(fs);
}

/* one-arg / two-arg boolean helpers */

static int call_bool(tdfsFS* fs, const char* method, td_val params) {
  td_val result;
  int rc;
  if (rpc_call(&fs->nn, method, params, &result)) return -1;
  rc = (result.t == TD_BOOL || result.t == TD_INT) ? (result.i ? 1 : 0) : 0;
  td_free(&result);
  return rc;
}

int tdfs_exists(tdfsFS* fs, const char* path) {
  td_val p = td_list(1);
  p.items[0] = td_text(path);
  return call_bool(fs, "exists", p);
}

int tdfs_mkdirs(tdfsFS* fs, const char* path) {
  td_val p = td_list(1);
  p.items[0] = td_text(path);
  return call_bool(fs, "mkdirs", p);
}

int tdfs_delete(tdfsFS* fs, const char* path, int recursive) {
  td_val p = td_list(2);
  p.items[0] = td_text(path);
  p.items[1] = td_bool(recursive);
  return call_bool(fs, "delete", p);
}

int tdfs_rename(tdfsFS* fs, const char* src, const char* dst) {
  td_val p = td_list(2);
  p.items[0] = td_text(src);
  p.items[1] = td_text(dst);
  return call_bool(fs, "rename", p);
}

int64_t tdfs_file_size(tdfsFS* fs, const char* path) {
  td_val p = td_list(1);
  td_val st;
  const td_val* len;
  int64_t out = -1;
  p.items[0] = td_text(path);
  if (rpc_call(&fs->nn, "get_status", p, &st)) return -1;
  len = td_get(&st, "length");
  if (len && len->t == TD_INT) out = len->i;
  td_free(&st);
  return out;
}

/* ------------------------------------------------------------ read */

static int dn_split(const char* addr, char* host, size_t hostsz, int* port) {
  const char* colon = strrchr(addr, ':');
  size_t hl;
  if (!colon) return -1;
  hl = (size_t)(colon - addr);
  if (hl + 1 > hostsz) return -1;
  memcpy(host, addr, hl);
  host[hl] = 0;
  *port = atoi(colon + 1);
  return 0;
}

char* tdfs_read_file(tdfsFS* fs, const char* path, int64_t* len_out) {
  td_val p = td_list(1);
  td_val blocks;
  char* out = NULL;
  size_t total = 0, off = 0, i, j;

  p.items[0] = td_text(path);
  if (rpc_call(&fs->nn, "get_block_locations", p, &blocks)) return NULL;
  if (blocks.t != TD_LIST) {
    td_free(&blocks);
    set_err("unexpected block list%s", NULL);
    return NULL;
  }
  for (i = 0; i < blocks.n; i++) {
    const td_val* sz = td_get(&blocks.items[i], "size");
    total += sz && sz->t == TD_INT ? (size_t)sz->i : 0;
  }
  out = (char*)malloc(total ? total : 1);

  for (i = 0; i < blocks.n; i++) {
    const td_val* bid = td_get(&blocks.items[i], "block_id");
    const td_val* locs = td_get(&blocks.items[i], "locations");
    int ok = 0;
    if (!bid || !locs || locs->t != TD_LIST) {
      free(out);
      td_free(&blocks);
      set_err("malformed block entry for %s", path);
      return NULL;
    }
    for (j = 0; j < locs->n && !ok; j++) {  /* replica failover */
      char host[256];
      int port;
      rpc_conn dn;
      td_val dp;
      td_val data = td_null();
      if (locs->items[j].t != TD_TEXT ||
          dn_split(locs->items[j].s, host, sizeof host, &port))
        continue;
      if (rpc_open(&dn, host, port)) continue;
      dp = td_list(1);
      dp.items[0] = td_int(bid->i);
      if (rpc_call(&dn, "read_block", dp, &data) == 0 &&
          data.t == TD_BYTES) {
        if (off + data.slen > total) {
          /* replica longer than NameNode metadata: corrupt/byzantine */
          td_free(&data);
          close(dn.fd);
          free(out);
          td_free(&blocks);
          set_err("replica larger than metadata for %s", path);
          return NULL;
        }
        memcpy(out + off, data.s, data.slen);
        off += data.slen;
        ok = 1;
      }
      td_free(&data);
      close(dn.fd);
    }
    if (!ok) {
      free(out);
      td_free(&blocks);
      set_err("no replica readable for a block of %s", path);
      return NULL;
    }
  }
  td_free(&blocks);
  *len_out = (int64_t)off;
  return out;
}

/* ------------------------------------------------------------ write */

int tdfs_write_file(tdfsFS* fs, const char* path, const char* data,
                    int64_t len) {
  td_val p = td_list(5);
  td_val meta;
  const td_val* bs;
  int64_t block_size, off = 0, prev = -1, last = -1;

  p.items[0] = td_text(path);
  p.items[1] = td_text(fs->client_name);
  p.items[2] = td_null();  /* replication: default */
  p.items[3] = td_null();  /* block size: default */
  p.items[4] = td_bool(1); /* overwrite */
  if (rpc_call(&fs->nn, "create", p, &meta)) return -1;
  bs = td_get(&meta, "block_size");
  block_size = bs && bs->t == TD_INT ? bs->i : (8 << 20);
  td_free(&meta);

  while (off < len || (len == 0 && off == 0)) {
    int64_t n = len - off < block_size ? len - off : block_size;
    td_val ap, alloc, wp, wres;
    const td_val* bid;
    const td_val* targets;
    char host[256];
    int port;
    rpc_conn dn;
    size_t k;

    if (len == 0) break; /* empty file: create+complete only */

    ap = td_list(4);
    ap.items[0] = td_text(path);
    ap.items[1] = td_text(fs->client_name);
    ap.items[2] = td_int(prev);
    ap.items[3] = td_list(0); /* excluded */
    if (rpc_call(&fs->nn, "add_block", ap, &alloc)) return -1;
    bid = td_get(&alloc, "block_id");
    targets = td_get(&alloc, "targets");
    if (!bid || !targets || targets->t != TD_LIST || targets->n == 0 ||
        targets->items[0].t != TD_TEXT ||
        dn_split(targets->items[0].s, host, sizeof host, &port)) {
      td_free(&alloc);
      set_err("bad block allocation for %s", path);
      return -1;
    }
    if (rpc_open(&dn, host, port)) {
      td_free(&alloc);
      return -1;
    }
    wp = td_list(3);
    wp.items[0] = td_int(bid->i);
    wp.items[1] = td_bytes(data + off, (size_t)n);
    wp.items[2] = td_list(targets->n - 1); /* downstream pipeline */
    for (k = 1; k < targets->n; k++)
      wp.items[2].items[k - 1] = td_text(targets->items[k].s);
    td_free(&alloc);
    if (rpc_call(&dn, "write_block", wp, &wres)) {
      close(dn.fd);
      return -1;
    }
    td_free(&wres);
    close(dn.fd);
    prev = n;
    last = n;
    off += n;
  }

  {
    td_val cp = td_list(3);
    td_val cres;
    cp.items[0] = td_text(path);
    cp.items[1] = td_text(fs->client_name);
    cp.items[2] = td_int(last);
    if (rpc_call(&fs->nn, "complete", cp, &cres)) return -1;
    td_free(&cres);
  }
  return 0;
}
