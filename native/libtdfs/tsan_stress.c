/* TSAN stress for libtdfs — the documented thread-safety contract is
 * "one tdfsFS per thread" (tdfs.h header comment): N threads, each with
 * its OWN handle, hammer one NameNode concurrently. Run compiled with
 * -fsanitize=thread this proves the library keeps NO racy shared state
 * behind that contract (the per-thread error buffer, the codec, and
 * the HMAC signer are the shared-code hot paths). SURVEY.md §5 race
 * detection: "TSAN-capable C++ where native".
 *
 * Usage: tsan_stress HOST PORT SECRET_FILE NTHREADS OPS
 *   (SECRET_FILE may be "-" for an open cluster)
 * Prints "clean" and exits 0 when every thread's ops all succeeded.
 */
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "tdfs.h"

typedef struct {
    const char* host;
    int port;
    const char* secret;
    int id;
    int ops;
    int failed;
} worker_arg;

static void* worker(void* p) {
    worker_arg* a = (worker_arg*)p;
    tdfsFS* fs = tdfs_connect_secure(a->host, a->port, a->secret);
    if (!fs) {
        fprintf(stderr, "t%d: connect: %s\n", a->id, tdfs_last_error());
        a->failed = 1;
        return NULL;
    }
    char dir[64], file[96], payload[256];
    snprintf(dir, sizeof dir, "/tsan/t%d", a->id);
    if (tdfs_mkdirs(fs, dir) != 1) {
        fprintf(stderr, "t%d: mkdirs: %s\n", a->id, tdfs_last_error());
        a->failed = 1;
        tdfs_disconnect(fs);
        return NULL;
    }
    for (int j = 0; j < a->ops && !a->failed; j++) {
        snprintf(file, sizeof file, "%s/f%d", dir, j);
        int n = snprintf(payload, sizeof payload,
                         "thread %d op %d payload", a->id, j);
        if (tdfs_write_file(fs, file, payload, n) != 0) {
            fprintf(stderr, "t%d: write %s: %s\n", a->id, file,
                    tdfs_last_error());
            a->failed = 1;
            break;
        }
        int64_t len = 0;
        char* back = tdfs_read_file(fs, file, &len);
        if (!back || len != n || memcmp(back, payload, (size_t)n) != 0) {
            fprintf(stderr, "t%d: readback mismatch %s: %s\n", a->id,
                    file, tdfs_last_error());
            a->failed = 1;
        }
        free(back);
        if (!a->failed && tdfs_exists(fs, file) != 1) {
            fprintf(stderr, "t%d: exists %s: %s\n", a->id, file,
                    tdfs_last_error());
            a->failed = 1;
        }
        /* exercise the per-thread error buffer concurrently: a lookup
         * that FAILS writes g_err on every thread at once */
        if (!a->failed && tdfs_file_size(fs, "/tsan/absent") != -1) {
            fprintf(stderr, "t%d: phantom file size\n", a->id);
            a->failed = 1;
        }
        if (!a->failed && tdfs_delete(fs, file, 0) != 1) {
            fprintf(stderr, "t%d: delete %s: %s\n", a->id, file,
                    tdfs_last_error());
            a->failed = 1;
        }
    }
    tdfs_disconnect(fs);
    return NULL;
}

int main(int argc, char** argv) {
    if (argc != 6) {
        fprintf(stderr,
                "usage: %s HOST PORT SECRET_FILE NTHREADS OPS\n",
                argv[0]);
        return 2;
    }
    const char* secret =
        (strcmp(argv[3], "-") == 0) ? NULL : argv[3];
    int nthreads = atoi(argv[4]);
    int ops = atoi(argv[5]);
    if (nthreads < 1 || nthreads > 64 || ops < 1) {
        fprintf(stderr, "bad NTHREADS/OPS\n");
        return 2;
    }
    pthread_t* tids = calloc((size_t)nthreads, sizeof *tids);
    worker_arg* args = calloc((size_t)nthreads, sizeof *args);
    if (!tids || !args) {
        fprintf(stderr, "oom\n");
        return 2;
    }
    for (int i = 0; i < nthreads; i++) {
        args[i] = (worker_arg){argv[1], atoi(argv[2]), secret, i, ops, 0};
        if (pthread_create(&tids[i], NULL, worker, &args[i]) != 0) {
            fprintf(stderr, "pthread_create failed\n");
            return 2;
        }
    }
    int failed = 0;
    for (int i = 0; i < nthreads; i++) {
        pthread_join(tids[i], NULL);
        failed |= args[i].failed;
    }
    free(tids);
    free(args);
    if (failed) return 1;
    printf("clean\n");
    return 0;
}
