/* libtdfs — C client for the tdfs replicated block store.
 *
 * ≈ the reference's libhdfs (src/c++/libhdfs/hdfs.h — the C FS API over
 * the Java client): connect to the NameNode, namespace operations, and
 * whole-file block-granular read/write through the DataNode protocol.
 * Speaks the framework's typed binary RPC codec natively (codec.h) —
 * no JNI/embedded-interpreter detour (the reference needed a JVM in
 * process; this is a plain TCP client).
 *
 * Thread safety: one tdfsFS per thread (connection state is per-handle).
 * Cluster auth: tdfs_connect_secure signs every request with
 * HMAC-SHA256 over the framework's canonical frame (hmac.h) — full
 * parity with authenticated Python clients (the reference's libhdfs
 * inherits auth via JNI; this client implements it natively).
 */
#ifndef TPUMR_TDFS_H
#define TPUMR_TDFS_H

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct tdfsFS_s tdfsFS;

/* Connect to a NameNode; NULL on failure (see tdfs_last_error). */
tdfsFS* tdfs_connect(const char* host, int port);

/* Connect to a secret-protected cluster: secret_file holds the cluster
 * secret (tpumr.rpc.secret.file semantics — surrounding whitespace
 * stripped). Pass NULL/"" for an open cluster. */
tdfsFS* tdfs_connect_secure(const char* host, int port,
                            const char* secret_file);

void tdfs_disconnect(tdfsFS* fs);

/* Namespace ops: 1 = yes/ok, 0 = no, -1 = error. */
int tdfs_exists(tdfsFS* fs, const char* path);
int tdfs_mkdirs(tdfsFS* fs, const char* path);
int tdfs_delete(tdfsFS* fs, const char* path, int recursive);
int tdfs_rename(tdfsFS* fs, const char* src, const char* dst);

/* File size in bytes, -1 on error. */
int64_t tdfs_file_size(tdfsFS* fs, const char* path);

/* Read a whole file. Returns a malloc'd buffer (caller frees), sets
 * *len_out; NULL on error. */
char* tdfs_read_file(tdfsFS* fs, const char* path, int64_t* len_out);

/* Create/overwrite a file with the given bytes (block-granular pipeline
 * writes under the hood). 0 on success, -1 on error. */
int tdfs_write_file(tdfsFS* fs, const char* path, const char* data,
                    int64_t len);

/* Last error message for this thread ("" if none). */
const char* tdfs_last_error(void);

#ifdef __cplusplus
}
#endif

#endif /* TPUMR_TDFS_H */
