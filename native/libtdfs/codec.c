/* Typed binary codec — see codec.h and tpumr/io/writable.py. */

#include "codec.h"

#include <stdlib.h>
#include <string.h>

/* ------------------------------------------------------------ values */

td_val td_null(void) { td_val v; memset(&v, 0, sizeof v); v.t = TD_NULL; return v; }

td_val td_int(int64_t x) { td_val v = td_null(); v.t = TD_INT; v.i = x; return v; }

td_val td_bool(int x) { td_val v = td_null(); v.t = TD_BOOL; v.i = x ? 1 : 0; return v; }

td_val td_float(double x) { td_val v = td_null(); v.t = TD_FLOAT; v.f = x; return v; }

td_val td_text(const char* s) {
  td_val v = td_null();
  v.t = TD_TEXT;
  v.slen = strlen(s);
  v.s = (char*)malloc(v.slen + 1);
  memcpy(v.s, s, v.slen + 1);
  return v;
}

td_val td_bytes(const char* data, size_t len) {
  td_val v = td_null();
  v.t = TD_BYTES;
  v.slen = len;
  v.s = (char*)malloc(len + 1);
  memcpy(v.s, data, len);
  v.s[len] = 0;
  return v;
}

td_val td_list(size_t n) {
  td_val v = td_null();
  v.t = TD_LIST;
  v.n = n;
  v.items = (td_val*)calloc(n ? n : 1, sizeof(td_val));
  return v;
}

td_val td_dict(size_t n_pairs) {
  td_val v = td_null();
  v.t = TD_DICT;
  v.n = n_pairs;
  v.items = (td_val*)calloc(n_pairs ? 2 * n_pairs : 1, sizeof(td_val));
  return v;
}

void td_free(td_val* v) {
  size_t i, count;
  if (!v) return;
  free(v->s);
  if (v->items) {
    count = (v->t == TD_DICT) ? 2 * v->n : v->n;
    for (i = 0; i < count; i++) td_free(&v->items[i]);
    free(v->items);
  }
  memset(v, 0, sizeof *v);
}

/* ------------------------------------------------------------ buffer */

void td_buf_init(td_buf* b) { b->data = NULL; b->len = b->cap = 0; }

void td_buf_free(td_buf* b) { free(b->data); td_buf_init(b); }

static void buf_put(td_buf* b, const void* p, size_t n) {
  if (b->len + n > b->cap) {
    size_t cap = b->cap ? b->cap * 2 : 256;
    while (cap < b->len + n) cap *= 2;
    b->data = (char*)realloc(b->data, cap);
    b->cap = cap;
  }
  memcpy(b->data + b->len, p, n);
  b->len += n;
}

static void buf_byte(td_buf* b, unsigned char c) { buf_put(b, &c, 1); }

/* ------------------------------------------------------------ encode */

static void enc_vint(td_buf* b, uint64_t v) {
  while (1) {
    unsigned char byte = v & 0x7F;
    v >>= 7;
    if (v) buf_byte(b, byte | 0x80);
    else { buf_byte(b, byte); return; }
  }
}

static uint64_t zigzag64(int64_t v) {
  /* no signed negation: -INT64_MIN is UB */
  return v >= 0 ? (uint64_t)v << 1 : ((~(uint64_t)v) << 1) | 1;
}

void td_encode(td_buf* out, const td_val* v) {
  size_t i;
  switch (v->t) {
    case TD_NULL: buf_byte(out, 0); break;
    case TD_BOOL: buf_byte(out, v->i ? 5 : 6); break;
    case TD_BYTES:
      buf_byte(out, 1);
      enc_vint(out, v->slen);
      buf_put(out, v->s, v->slen);
      break;
    case TD_TEXT:
      buf_byte(out, 2);
      enc_vint(out, v->slen);
      buf_put(out, v->s, v->slen);
      break;
    case TD_INT:
      buf_byte(out, 3);
      enc_vint(out, zigzag64(v->i));
      break;
    case TD_FLOAT: {
      unsigned char be[8];
      uint64_t bits;
      memcpy(&bits, &v->f, 8);
      for (i = 0; i < 8; i++) be[i] = (unsigned char)(bits >> (56 - 8 * i));
      buf_byte(out, 4);
      buf_put(out, be, 8);
      break;
    }
    case TD_LIST:
      buf_byte(out, 7);
      enc_vint(out, v->n);
      for (i = 0; i < v->n; i++) td_encode(out, &v->items[i]);
      break;
    case TD_DICT:
      buf_byte(out, 9);
      enc_vint(out, v->n);
      for (i = 0; i < 2 * v->n; i++) td_encode(out, &v->items[i]);
      break;
  }
}

/* ------------------------------------------------------------ decode */

static int dec_vint(const char* d, size_t len, size_t* pos, uint64_t* out) {
  uint64_t result = 0;
  int shift = 0;
  while (*pos < len) {
    unsigned char b = (unsigned char)d[(*pos)++];
    result |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) { *out = result; return 0; }
    shift += 7;
    if (shift > 63) return -1;
  }
  return -1;
}

static int64_t unzigzag64(uint64_t v) {
  /* branchless standard form: correct for v = UINT64_MAX (INT64_MIN),
   * where the naive -(int64_t)((v + 1) >> 1) wraps v+1 to 0 */
  return (int64_t)(v >> 1) ^ -(int64_t)(v & 1);
}

/* Containers nest by recursion: cap the depth so a frame of nested
 * list tags (2 bytes/level) can't overflow the C stack. The protocol's
 * real structures are < 10 deep. */
#define TD_MAX_DEPTH 64

static int decode_impl(const char* d, size_t len, size_t* pos, td_val* out,
                       int depth) {
  uint64_t n;
  size_t i;
  unsigned char tag;
  *out = td_null();
  if (depth > TD_MAX_DEPTH) return -1;
  if (*pos >= len) return -1;
  tag = (unsigned char)d[(*pos)++];
  switch (tag) {
    case 0: return 0;
    case 5: *out = td_bool(1); return 0;
    case 6: *out = td_bool(0); return 0;
    case 1:
    case 2:
      if (dec_vint(d, len, pos, &n)) return -1;
      /* compare against the REMAINDER: "*pos + n > len" wraps for huge
       * n off the wire and would pass the check into an OOB memcpy */
      if (n > len - *pos) return -1;
      out->s = (char*)malloc((size_t)n + 1);
      if (!out->s) return -1;
      memcpy(out->s, d + *pos, n);
      out->s[n] = 0;
      out->t = (tag == 1) ? TD_BYTES : TD_TEXT;
      out->slen = (size_t)n;
      *pos += n;
      return 0;
    case 3:
      if (dec_vint(d, len, pos, &n)) return -1;
      *out = td_int(unzigzag64(n));
      return 0;
    case 4: {
      uint64_t bits = 0;
      if (len - *pos < 8) return -1;
      for (i = 0; i < 8; i++)
        bits = (bits << 8) | (unsigned char)d[*pos + i];
      *pos += 8;
      out->t = TD_FLOAT;
      memcpy(&out->f, &bits, 8);
      return 0;
    }
    case 7:
      if (dec_vint(d, len, pos, &n)) return -1;
      /* each element needs >= 1 byte: bound against remaining input so a
       * malicious count can't drive a huge/failed allocation */
      if (n > len - *pos) return -1;
      *out = td_list(n);
      if (!out->items) return -1;
      for (i = 0; i < n; i++)
        if (decode_impl(d, len, pos, &out->items[i], depth + 1)) {
          td_free(out);
          return -1;
        }
      return 0;
    case 9:
      if (dec_vint(d, len, pos, &n)) return -1;
      if (n > (len - *pos) / 2 + 1) return -1;
      *out = td_dict(n);
      if (!out->items) return -1;
      for (i = 0; i < 2 * n; i++)
        if (decode_impl(d, len, pos, &out->items[i], depth + 1)) {
          td_free(out);
          return -1;
        }
      return 0;
    default:
      /* tag 8 (ndarray) and unknown tags unsupported in C */
      return -1;
  }
}

int td_decode(const char* d, size_t len, size_t* pos, td_val* out) {
  return decode_impl(d, len, pos, out, 0);
}

const td_val* td_get(const td_val* dict, const char* key) {
  size_t i;
  if (!dict || dict->t != TD_DICT) return NULL;
  for (i = 0; i < dict->n; i++) {
    const td_val* k = &dict->items[2 * i];
    if (k->t == TD_TEXT && strcmp(k->s, key) == 0)
      return &dict->items[2 * i + 1];
  }
  return NULL;
}
