/* SHA-256 + HMAC-SHA256 for libtdfs RPC signing.
 *
 * ≈ the role DIGEST-MD5/SASL plays for the reference's libhdfs-over-JNI
 * client (the Java client brings its own auth; this C client signs the
 * framework's HMAC-SHA256 frames natively, tpumr/ipc/rpc.py:_sign).
 * SHA-256 implemented from FIPS 180-4; no external dependencies.
 */
#ifndef TPUMR_HMAC_H
#define TPUMR_HMAC_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct {
  uint32_t h[8];
  uint64_t len;          /* total message bytes */
  unsigned char buf[64];
  size_t buflen;
} td_sha256_ctx;

void td_sha256_init(td_sha256_ctx* c);
void td_sha256_update(td_sha256_ctx* c, const void* data, size_t n);
void td_sha256_final(td_sha256_ctx* c, unsigned char out[32]);

/* HMAC-SHA256(key, msg) -> 64-char lowercase hex + NUL. */
void td_hmac_sha256_hex(const void* key, size_t keylen,
                        const void* msg, size_t msglen,
                        char out_hex[65]);

#ifdef __cplusplus
}
#endif

#endif /* TPUMR_HMAC_H */
