/* tokencount — single-pass whitespace tokenize + count for wordcount.
 *
 * The role of the reference's per-line WordCount mapper hot loop
 * (examples/WordCount.java StringTokenizer; pipes wordcount-simple.cc),
 * rebuilt as native batch code: one pass over the whole split's bytes,
 * open-addressing FNV-1a hash table of (token-pointer, len) -> count —
 * tokens are NOT copied, they point into the caller's buffer. Token
 * semantics are exactly Python bytes.split(): the six ASCII whitespace
 * separators, no empty tokens.
 *
 * Result buffer layout (malloc'd, caller frees via tc_free):
 *   u64 n_entries, then per entry: u32 len, u64 count, len token bytes.
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

typedef struct {
  const unsigned char* tok;
  uint32_t len;
  uint64_t count;
} slot_t;

static const unsigned char WS[256] = {
  [9] = 1, [10] = 1, [11] = 1, [12] = 1, [13] = 1, [32] = 1,
};

/* Chunked multiply-xor hash: 8 bytes per multiply instead of FNV's
 * one — tokenizing was measured hash-bound (the boundary scan itself is
 * a table lookup per byte; the per-byte multiply dominated). Murmur-style
 * finalizer keeps the open-addressing probes well distributed. */
static uint64_t hash_tok(const unsigned char* p, uint32_t n) {
  uint64_t h = 0x9E3779B97F4A7C15ull ^ n;
  uint32_t rem = n;
  while (rem >= 8) {
    uint64_t x;
    memcpy(&x, p, 8);
    h = (h ^ x) * 0xFF51AFD7ED558CCDull;
    h ^= h >> 29;
    p += 8;
    rem -= 8;
  }
  if (rem) {
    uint64_t x = 0;
    memcpy(&x, p, rem);
    h = (h ^ x) * 0xC4CEB9FE1A85EC53ull;
  }
  /* full avalanche (murmur3 fmix64): multiplication only carries
   * entropy UPWARD, so without this the table's low index bits depend
   * only on the first bytes of the token — same-prefix corpora
   * (word0001…word4095) collapse every slot probe into one cluster */
  h ^= h >> 33;
  h *= 0xFF51AFD7ED558CCDull;
  h ^= h >> 33;
  return h;
}

typedef struct {
  slot_t* slots;
  uint64_t cap;     /* power of two */
  uint64_t used;
} table_t;

static int grow(table_t* t) {
  uint64_t ncap = t->cap ? t->cap * 2 : 4096;
  slot_t* ns = (slot_t*)calloc(ncap, sizeof(slot_t));
  uint64_t i;
  if (!ns) return -1;
  for (i = 0; i < t->cap; i++) {
    slot_t* s = &t->slots[i];
    if (s->tok) {
      uint64_t j = hash_tok(s->tok, s->len) & (ncap - 1);
      while (ns[j].tok) j = (j + 1) & (ncap - 1);
      ns[j] = *s;
    }
  }
  free(t->slots);
  t->slots = ns;
  t->cap = ncap;
  return 0;
}

static int bump(table_t* t, const unsigned char* tok, uint32_t len,
                uint64_t h) {
  uint64_t j;
  if (t->used * 10 >= t->cap * 7 && grow(t)) return -1;
  j = h & (t->cap - 1);
  for (;;) {
    slot_t* s = &t->slots[j];
    if (!s->tok) {
      s->tok = tok;
      s->len = len;
      s->count = 1;
      t->used++;
      return 0;
    }
    if (s->len == len && memcmp(s->tok, tok, len) == 0) {
      s->count++;
      return 0;
    }
    j = (j + 1) & (t->cap - 1);
  }
}

char* tc_count(const unsigned char* data, uint64_t n, uint64_t* out_len) {
  table_t t = {0, 0, 0};
  uint64_t i = 0, total, k, w;
  char* out;
  if (grow(&t)) return NULL;
  while (i < n) {
    uint64_t start, h;
    while (i < n && WS[data[i]]) i++;
    start = i;
    /* boundary scan is a bare table lookup per byte; the token hashes
     * afterwards in 8-byte chunks (hash_tok) — measured ~2x over
     * hashing inline per byte */
    while (i < n && !WS[data[i]]) i++;
    if (i > start) {
      h = hash_tok(data + start, (uint32_t)(i - start));
      if (bump(&t, data + start, (uint32_t)(i - start), h)) {
        free(t.slots);
        return NULL;
      }
    }
  }
  total = 8;
  for (k = 0; k < t.cap; k++)
    if (t.slots[k].tok) total += 12 + t.slots[k].len;
  out = (char*)malloc(total);
  if (!out) {
    free(t.slots);
    return NULL;
  }
  memcpy(out, &t.used, 8);
  w = 8;
  for (k = 0; k < t.cap; k++) {
    slot_t* s = &t.slots[k];
    if (!s->tok) continue;
    memcpy(out + w, &s->len, 4);
    memcpy(out + w + 4, &s->count, 8);
    memcpy(out + w + 12, s->tok, s->len);
    w += 12 + s->len;
  }
  free(t.slots);
  *out_len = total;
  return out;
}

void tc_free(char* p) { free(p); }
