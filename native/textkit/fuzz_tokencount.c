/* fuzz_tokencount — deterministic fuzz + property check for the
 * single-pass tokenizer (tokencount.c), which runs over arbitrary split
 * bytes handed in by the wordcount job.
 *
 * Properties checked each iteration (ASAN+UBSAN catch the memory side):
 *   - the result buffer parses: entry lens stay in bounds, n_entries
 *     matches the walked count
 *   - sum(count) equals a naive independent token count
 *   - every emitted token contains no whitespace byte
 *
 * argv: [iterations] [corpus-dir]
 */

#include <dirent.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

char* tc_count(const unsigned char* data, uint64_t n, uint64_t* out_len);
void tc_free(char* p);

static uint64_t rng_state;

static uint64_t rnd(void) {
  uint64_t x = rng_state;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  return rng_state = x;
}

static int is_ws(unsigned char c) {
  return c == 9 || c == 10 || c == 11 || c == 12 || c == 13 || c == 32;
}

static uint64_t naive_tokens(const unsigned char* d, uint64_t n) {
  uint64_t i = 0, count = 0;
  while (i < n) {
    while (i < n && is_ws(d[i])) i++;
    if (i < n) count++;
    while (i < n && !is_ws(d[i])) i++;
  }
  return count;
}

static int check(const unsigned char* data, uint64_t n) {
  uint64_t out_len = 0, entries, total = 0, w = 8, k, i;
  char* out = tc_count(data, n, &out_len);
  if (!out) {
    fprintf(stderr, "FUZZ FAIL: tc_count returned NULL for %llu bytes\n",
            (unsigned long long)n);
    return -1;
  }
  if (out_len < 8) {
    fprintf(stderr, "FUZZ FAIL: result shorter than header\n");
    tc_free(out);
    return -1;
  }
  memcpy(&entries, out, 8);
  for (k = 0; k < entries; k++) {
    uint32_t len;
    uint64_t count;
    if (w + 12 > out_len) {
      fprintf(stderr, "FUZZ FAIL: entry %llu header out of bounds\n",
              (unsigned long long)k);
      tc_free(out);
      return -1;
    }
    memcpy(&len, out + w, 4);
    memcpy(&count, out + w + 4, 8);
    if (w + 12 + len > out_len || len == 0 || count == 0) {
      fprintf(stderr, "FUZZ FAIL: entry %llu malformed\n",
              (unsigned long long)k);
      tc_free(out);
      return -1;
    }
    for (i = 0; i < len; i++)
      if (is_ws((unsigned char)out[w + 12 + i])) {
        fprintf(stderr, "FUZZ FAIL: token contains whitespace\n");
        tc_free(out);
        return -1;
      }
    total += count;
    w += 12 + len;
  }
  if (w != out_len) {
    fprintf(stderr, "FUZZ FAIL: trailing bytes after last entry\n");
    tc_free(out);
    return -1;
  }
  if (total != naive_tokens(data, n)) {
    fprintf(stderr, "FUZZ FAIL: count mismatch %llu vs naive %llu\n",
            (unsigned long long)total,
            (unsigned long long)naive_tokens(data, n));
    tc_free(out);
    return -1;
  }
  tc_free(out);
  return 0;
}

static int fuzz_corpus_file(const char* path) {
  FILE* f = fopen(path, "rb");
  unsigned char* data;
  long sz;
  int rc;
  if (!f) return 0;
  fseek(f, 0, SEEK_END);
  sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (sz < 0 || sz > 4 << 20) {
    fclose(f);
    return 0;
  }
  data = (unsigned char*)malloc(sz ? (size_t)sz : 1);
  if (fread(data, 1, (size_t)sz, f) != (size_t)sz) sz = 0;
  fclose(f);
  rc = check(data, (uint64_t)sz);
  free(data);
  return rc;
}

int main(int argc, char** argv) {
  long iters = argc > 1 ? atol(argv[1]) : 1000;
  long it;
  for (it = 0; it < iters; it++) {
    unsigned char buf[2048];
    size_t n, i;
    rng_state = 0xC0FFEE ^ (uint64_t)it * 0x9E3779B97F4A7C15ull;
    n = rnd() % sizeof buf;
    for (i = 0; i < n; i++) {
      /* bias: ~1/4 whitespace, mix of repeated and arbitrary bytes */
      uint64_t r = rnd();
      if ((r & 3) == 0)
        buf[i] = " \t\n\v\f\r"[r % 6];
      else if ((r & 3) == 1)
        buf[i] = (unsigned char)('a' + r % 4);   /* heavy collisions */
      else
        buf[i] = (unsigned char)r;
    }
    if (check(buf, n)) return 1;
    if (n) {                    /* no trailing separator */
      while (n && is_ws(buf[n - 1])) n--;
      if (check(buf, n)) return 1;
    }
  }
  if (argc > 2) {
    DIR* d = opendir(argv[2]);
    struct dirent* e;
    if (d) {
      while ((e = readdir(d)) != NULL) {
        char path[4096];
        if (e->d_name[0] == '.') continue;
        snprintf(path, sizeof path, "%s/%s", argv[2], e->d_name);
        if (fuzz_corpus_file(path)) return 1;
      }
      closedir(d);
    }
  }
  printf("fuzz_tokencount: %ld iterations clean\n", iters);
  return 0;
}
