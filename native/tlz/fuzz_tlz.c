/* fuzz_tlz — deterministic fuzz + property checks for the tlz codec.
 *
 * A: roundtrip property on generated payloads spanning the codec's
 *    regimes (repetitive text-like, random, mixed, tiny).
 * B: decompress of MUTATED valid frames must only ever return -1 or a
 *    (possibly wrong) payload — never crash/overrun (ASAN enforces).
 * C: random garbage into tlz_decompress.
 *
 * argv: [iterations]
 */

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

uint64_t tlz_bound(uint64_t n);
int64_t tlz_compress(const uint8_t* src, uint64_t n, uint8_t* dst,
                     uint64_t cap);
int64_t tlz_raw_size(const uint8_t* src, uint64_t n);
int64_t tlz_decompress(const uint8_t* src, uint64_t n, uint8_t* dst,
                       uint64_t cap);

static uint64_t rng_state;

static uint64_t rnd(void) {
  uint64_t x = rng_state;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  return rng_state = x;
}

static uint64_t gen_payload(uint8_t* buf, uint64_t cap) {
  uint64_t n = rnd() % cap, i, mode = rnd() % 4;
  if (mode == 0) {                      /* repetitive text-like */
    const char* words[4] = {"alpha ", "beta ", "gamma7 ", "x"};
    uint64_t w = 0;
    while (w < n) {
      const char* s = words[rnd() % 4];
      uint64_t l = strlen(s);
      if (w + l > n) break;
      memcpy(buf + w, s, l);
      w += l;
    }
    return w;
  }
  if (mode == 1) {                      /* pure random */
    for (i = 0; i < n; i++) buf[i] = (uint8_t)rnd();
    return n;
  }
  if (mode == 2) {                      /* long runs (overlap copies) */
    uint64_t w = 0;
    while (w < n) {
      uint8_t c = (uint8_t)rnd();
      uint64_t run = 1 + rnd() % 300;
      for (i = 0; i < run && w < n; i++) buf[w++] = c;
    }
    return w;
  }
  for (i = 0; i < n; i++)               /* mixed */
    buf[i] = (rnd() & 1) ? (uint8_t)(rnd() % 4) : (uint8_t)rnd();
  return n;
}

int main(int argc, char** argv) {
  long iters = argc > 1 ? atol(argv[1]) : 800;
  enum { CAP = 1 << 16 };
  uint8_t* raw = malloc(CAP);
  uint8_t* comp = malloc(tlz_bound(CAP));
  uint8_t* mut = malloc(tlz_bound(CAP));
  uint8_t* back = malloc(CAP);
  long it;
  for (it = 0; it < iters; it++) {
    uint64_t n;
    int64_t c, d;
    rng_state = 0x7152DEAD ^ (uint64_t)it * 0x9E3779B97F4A7C15ull;
    n = gen_payload(raw, CAP);
    c = tlz_compress(raw, n, comp, tlz_bound(CAP));
    if (c < 0) {
      fprintf(stderr, "FUZZ FAIL: compress returned %lld for %llu\n",
              (long long)c, (unsigned long long)n);
      return 1;
    }
    if (tlz_raw_size(comp, (uint64_t)c) != (int64_t)n) {
      fprintf(stderr, "FUZZ FAIL: raw_size mismatch\n");
      return 1;
    }
    d = tlz_decompress(comp, (uint64_t)c, back, CAP);
    if (d != (int64_t)n || (n && memcmp(raw, back, n) != 0)) {
      fprintf(stderr, "FUZZ FAIL: roundtrip (%llu -> %lld -> %lld)\n",
              (unsigned long long)n, (long long)c, (long long)d);
      return 1;
    }
    /* B: mutate the valid frame */
    {
      int m;
      for (m = 0; m < 16; m++) {
        uint64_t cut = (uint64_t)c ? 1 + rnd() % (uint64_t)c : 0;
        int f;
        memcpy(mut, comp, (size_t)c);
        for (f = 0; f < 4; f++)
          mut[rnd() % (c ? (uint64_t)c : 1)] = (uint8_t)rnd();
        tlz_decompress(mut, (uint64_t)c, back, CAP);
        tlz_decompress(mut, cut, back, CAP);
      }
    }
    /* C: garbage */
    {
      uint64_t gn = rnd() % 512, i;
      for (i = 0; i < gn; i++) comp[i] = (uint8_t)rnd();
      tlz_decompress(comp, gn, back, CAP);
    }
  }
  printf("fuzz_tlz: %ld iterations clean\n", iters);
  free(raw);
  free(comp);
  free(mut);
  free(back);
  return 0;
}
