/* tlz — the framework's fast shuffle/spill codec.
 *
 * Role of the reference's JNI compression tier (src/native/src/org/
 * apache/hadoop/io/compress/ — shipped native zlib/snappy because map
 * output compression sits on the spill/shuffle hot path). Measured here
 * (bench_details codec rows): Python's zlib tops out ~134 MB/s at
 * level 1 on text-like spills — below the pipeline's own throughput —
 * and wastes ~40 MB/s achieving nothing on incompressible data. This
 * is an ORIGINAL byte-oriented LZ77 implementation (greedy hash-4
 * matching, 64 KB window, LZ4-class speed target) with its own frame
 * format; we control both ends of the wire, so no interop format is
 * needed.
 *
 * Frame: 'T' 'L' 'Z' ver, u64 LE raw length, payload.
 *   ver '0' — stored raw (compressor found the input incompressible:
 *             memcpy-speed passthrough instead of negative-gain work)
 *   ver '1' — LZ payload: sequences of
 *       token byte   (lit_len in high nibble, match_len-4 in low)
 *       [lit ext]    if lit_len == 15: bytes of 255 + terminator added
 *       literals
 *       u16 LE offset (1..65535, match source = out_pos - offset)
 *       [match ext]  if match_len-4 == 15: same extension coding
 *     The final sequence may end after its literals (offset omitted)
 *     exactly when the raw length is reached.
 *
 * The decompressor bounds-checks every read and write: corrupt or
 * hostile frames return -1, never overrun (fuzzed under ASAN/UBSAN by
 * fuzz_tlz.c like the other native parsers).
 */

#include <stdint.h>
#include <string.h>

#define TLZ_WINDOW 65535u
#define TLZ_MIN_MATCH 4u
#define TLZ_HASH_BITS 15
#define TLZ_HASH_SIZE (1u << TLZ_HASH_BITS)

uint64_t tlz_bound(uint64_t n) {
  /* worst case: all literals with extension bytes, plus frame header */
  return n + n / 255 + 32;
}

static uint32_t read32(const uint8_t* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

static uint32_t hash4(uint32_t v) {
  return (v * 2654435761u) >> (32 - TLZ_HASH_BITS);
}

static void put_u64(uint8_t* p, uint64_t v) {
  int i;
  for (i = 0; i < 8; i++) p[i] = (uint8_t)(v >> (8 * i));
}

static uint64_t get_u64(const uint8_t* p) {
  uint64_t v = 0;
  int i;
  for (i = 7; i >= 0; i--) v = (v << 8) | p[i];
  return v;
}

/* write the length-extension coding: bytes of 255 then remainder */
static uint64_t put_ext(uint8_t* dst, uint64_t cap, uint64_t w,
                        uint64_t v) {
  while (v >= 255) {
    if (w >= cap) return (uint64_t)-1;
    dst[w++] = 255;
    v -= 255;
  }
  if (w >= cap) return (uint64_t)-1;
  dst[w++] = (uint8_t)v;
  return w;
}

static int64_t store_raw(const uint8_t* src, uint64_t n, uint8_t* dst,
                         uint64_t cap) {
  if (cap < n + 12) return -1;
  dst[0] = 'T'; dst[1] = 'L'; dst[2] = 'Z'; dst[3] = '0';
  put_u64(dst + 4, n);
  memcpy(dst + 12, src, n);
  return (int64_t)(n + 12);
}

int64_t tlz_compress(const uint8_t* src, uint64_t n, uint8_t* dst,
                     uint64_t cap) {
  static const uint64_t HDR = 12;
  uint32_t tab[TLZ_HASH_SIZE];
  uint64_t w = HDR, pos = 0, lit_start = 0, misses = 0;
  if (cap < HDR) return -1;
  if (n < 16) return store_raw(src, n, dst, cap);
  memset(tab, 0xFF, sizeof tab);
  while (pos + TLZ_MIN_MATCH <= n) {
    uint32_t v = read32(src + pos);
    uint32_t h = hash4(v);
    uint32_t cand = tab[h];
    tab[h] = (uint32_t)pos;
    if (cand != 0xFFFFFFFFu && (uint64_t)cand < pos &&
        pos - cand <= TLZ_WINDOW && read32(src + cand) == v) {
      /* extend the match forward */
      uint64_t mlen = TLZ_MIN_MATCH;
      uint64_t lit = pos - lit_start;
      uint64_t mtok, offset = pos - cand;
      while (pos + mlen < n &&
             src[cand + mlen] == src[pos + mlen])
        mlen++;
      /* token + extensions + literals + offset */
      mtok = mlen - TLZ_MIN_MATCH;
      if (w >= cap) return store_raw(src, n, dst, cap);
      dst[w++] = (uint8_t)(((lit < 15 ? lit : 15) << 4)
                           | (mtok < 15 ? mtok : 15));
      if (lit >= 15) {
        w = put_ext(dst, cap, w, lit - 15);
        if (w == (uint64_t)-1) return store_raw(src, n, dst, cap);
      }
      if (w + lit + 2 > cap) return store_raw(src, n, dst, cap);
      memcpy(dst + w, src + lit_start, lit);
      w += lit;
      dst[w++] = (uint8_t)(offset & 0xFF);
      dst[w++] = (uint8_t)(offset >> 8);
      if (mtok >= 15) {
        w = put_ext(dst, cap, w, mtok - 15);
        if (w == (uint64_t)-1) return store_raw(src, n, dst, cap);
      }
      /* seed the table through the matched region (sparsely: every
       * other position is plenty for this codec's speed class) */
      {
        uint64_t p2 = pos + 1, end = pos + mlen;
        for (; p2 + TLZ_MIN_MATCH <= end && p2 + 4 <= n; p2 += 2)
          tab[hash4(read32(src + p2))] = (uint32_t)p2;
      }
      pos += mlen;
      lit_start = pos;
      misses = 0;
    } else {
      /* skip-accelerator: incompressible regions fast-forward so a
       * random 100 MB spill doesn't crawl through every byte */
      pos += 1 + (misses >> 6);
      misses++;
    }
  }
  /* tail literals */
  {
    uint64_t lit = n - lit_start;
    if (w >= cap) return store_raw(src, n, dst, cap);
    dst[w++] = (uint8_t)((lit < 15 ? lit : 15) << 4);
    if (lit >= 15) {
      w = put_ext(dst, cap, w, lit - 15);
      if (w == (uint64_t)-1) return store_raw(src, n, dst, cap);
    }
    if (w + lit > cap) return store_raw(src, n, dst, cap);
    memcpy(dst + w, src + lit_start, lit);
    w += lit;
  }
  if (w >= n + HDR)  /* no gain: ship stored for memcpy decompression */
    return store_raw(src, n, dst, cap);
  dst[0] = 'T'; dst[1] = 'L'; dst[2] = 'Z'; dst[3] = '1';
  put_u64(dst + 4, n);
  return (int64_t)w;
}

int64_t tlz_raw_size(const uint8_t* src, uint64_t n) {
  if (n < 12 || src[0] != 'T' || src[1] != 'L' || src[2] != 'Z')
    return -1;
  if (src[3] != '0' && src[3] != '1') return -1;
  return (int64_t)get_u64(src + 4);
}

/* read one extended length; returns updated r or -1 on overrun */
static uint64_t get_ext(const uint8_t* src, uint64_t n, uint64_t r,
                        uint64_t* v) {
  for (;;) {
    uint8_t b;
    if (r >= n) return (uint64_t)-1;
    b = src[r++];
    *v += b;
    if (b != 255) return r;
  }
}

int64_t tlz_decompress(const uint8_t* src, uint64_t n, uint8_t* dst,
                       uint64_t cap) {
  uint64_t raw, r = 12, w = 0;
  int64_t hdr = tlz_raw_size(src, n);
  if (hdr < 0) return -1;
  raw = (uint64_t)hdr;
  if (raw > cap) return -1;
  if (src[3] == '0') {
    if (n - 12 != raw) return -1;
    memcpy(dst, src + 12, raw);
    return (int64_t)raw;
  }
  while (w < raw) {
    uint64_t lit, mlen, offset;
    uint8_t token;
    if (r >= n) return -1;
    token = src[r++];
    lit = token >> 4;
    if (lit == 15) {
      r = get_ext(src, n, r, &lit);
      if (r == (uint64_t)-1) return -1;
    }
    if (lit > n - r || lit > raw - w) return -1;
    memcpy(dst + w, src + r, lit);
    r += lit;
    w += lit;
    if (w == raw) break;          /* final literal-only sequence */
    mlen = (uint64_t)(token & 0xF);
    if (r + 2 > n) return -1;
    offset = (uint64_t)src[r] | ((uint64_t)src[r + 1] << 8);
    r += 2;
    if (mlen == 15) {
      r = get_ext(src, n, r, &mlen);
      if (r == (uint64_t)-1) return -1;
    }
    mlen += TLZ_MIN_MATCH;
    if (offset == 0 || offset > w || mlen > raw - w) return -1;
    /* overlapping copy must run forward byte-wise (offset < mlen
     * replicates the window — the classic LZ run encoding) */
    {
      const uint8_t* from = dst + (w - offset);
      uint64_t i;
      for (i = 0; i < mlen; i++) dst[w + i] = from[i];
    }
    w += mlen;
  }
  if (w != raw) return -1;
  return (int64_t)raw;
}
