# Shared sanitizer configuration for every native tier's fuzz/ASAN
# targets — change instrumentation HERE, not per-Makefile (a missed copy
# silently runs a tier with weaker checking).
SANFLAGS := -fsanitize=address,undefined -fno-sanitize-recover=all \
  -fno-omit-frame-pointer -g -O1

# ThreadSanitizer (mutually exclusive with ASAN — separate binaries)
TSANFLAGS := -fsanitize=thread -fno-omit-frame-pointer -g -O1
